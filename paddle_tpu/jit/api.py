"""to_static: trace-and-compile.

TPU-native replacement for the reference's entire dygraph→static bridge:
 - ``@to_static`` AST transformation (``python/paddle/jit/api.py:233``,
   ``jit/dy2static/ast_transformer.py``) — NOT needed: jax tracing handles
   python control flow natively (structured control flow via lax.cond/scan
   where data-dependent).
 - ``PartialProgramLayer`` + run_program op (``partial_program.py:150``,
   ``paddle/fluid/eager/to_static/run_program_op_func.h:56``) — replaced by
   one jitted pure function over (params, buffers, rng key, inputs).
 - ``_ExecutorCache`` (``fluid/executor.py:701``) — replaced by jax.jit's
   compile cache keyed on shapes/dtypes plus our static keys (arg tree
   structure, python-scalar args, training mode).

Eager interop: a call to a StaticFunction records ONE tape node whose vjp is
the compiled backward — `loss.backward()` on a to_static model runs a fully
compiled forward+backward.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .. import autograd
from ..framework import random as _random
from ..nn.layer.layers import Layer

__all__ = ["to_static", "not_to_static", "StaticFunction", "InputSpec",
           "functional_call", "enable_static", "disable_static",
           "in_dynamic_mode", "ignore_module"]

# static/graph.py owns the one mode flag; these delegate (single source of
# truth — a desync would make Optimizer/record disagree about the mode)
def enable_static():
    """Switch to static-graph mode: ops on ``static.data`` placeholders
    record into the default Program (see ``paddle_tpu.static``)."""
    from ..static import graph as _g
    _g.enable_static()


def disable_static():
    from ..static import graph as _g
    _g.disable_static()


def in_dynamic_mode():
    from ..static import graph as _g
    return not _g.in_static_mode()


def ignore_module(modules):
    return None


class InputSpec:
    """Shape/dtype declaration (ref: ``paddle.static.InputSpec``).
    None dims mean dynamic; to_static buckets compilation per concrete
    shape (XLA requires static shapes)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def functional_call(layer: Layer, params: dict, buffers: dict, args=(),
                    kwargs=None, training=None, forward_fn=None):
    """Run `layer` as a pure function of (params, buffers, inputs).

    Swaps the given arrays into the layer's parameter/buffer tensors, calls
    forward, and returns (outputs, new_buffer_arrays). The layer's own
    arrays are restored afterwards. This is the bridge that lets the
    object-oriented Layer API compile to a single XLA program.
    """
    kwargs = kwargs or {}
    named_p = dict(layer.named_parameters())
    named_b = dict(layer.named_buffers())
    saved_p = {k: t._data for k, t in named_p.items()}
    saved_b = {k: t._data for k, t in named_b.items()}
    saved_training = layer.training
    try:
        for k, arr in params.items():
            named_p[k]._data = arr
        for k, arr in buffers.items():
            named_b[k]._data = arr
        if training is not None and training != layer.training:
            layer.train() if training else layer.eval()
        with autograd.functional_guard():
            # forward_fn overrides dispatch through layer.__call__ — needed
            # when layer.forward itself has been replaced by a
            # StaticFunction (to_static(layer)) to avoid re-entry
            out = forward_fn(*args, **kwargs) if forward_fn is not None \
                else layer(*args, **kwargs)
        new_buffers = {k: named_b[k]._data for k in buffers}
        return out, new_buffers
    finally:
        for k, arr in saved_p.items():
            named_p[k]._data = arr
        for k, arr in saved_b.items():
            named_b[k]._data = arr
        if training is not None and layer.training != saved_training:
            layer.train() if saved_training else layer.eval()


def _is_arraylike(x):
    return isinstance(x, (jax.Array, jax.core.Tracer, np.ndarray))


def _closure_layer_targets(fn):
    """(prefix, Layer) pairs a plain function closes over.

    ``to_static`` on a bare function (not a Layer) must still thread the
    parameters of any Layer captured in the function's closure (or bound
    ``self``) through the jitted program as real inputs — otherwise they
    trace as constants, no tape node is recorded, and ``backward()``
    silently produces no gradients (the failure is invisible: the loss
    simply never moves). Ref: dy2static resolves the same case through
    its live-variable analysis (``program_translator.py``).
    """
    out, seen = [], set()

    def add(prefix, val):
        if isinstance(val, Layer) and id(val) not in seen:
            seen.add(id(val))
            out.append((prefix, val))

    def add_container(name, val):
        add(name, val)
        if isinstance(val, (list, tuple)):
            for i, v in enumerate(val):
                add(f"{name}.{i}", v)
        elif isinstance(val, dict):
            for k, v in val.items():
                add(f"{name}.{k}", v)

    obj = getattr(fn, "__self__", None)
    if obj is not None:
        add("self", obj)
    raw = getattr(fn, "__wrapped__", fn)
    code = getattr(raw, "__code__", None)
    cells = getattr(raw, "__closure__", None) or ()
    names = code.co_freevars if code is not None else ()
    for name, cell in zip(names, cells):
        try:
            val = cell.cell_contents
        except ValueError:
            continue
        add_container(name, val)
    # module-level globals the code object LOADS as globals — the most
    # common script style (`net = Linear(...)` at top level). Uses real
    # LOAD_GLOBAL instructions, not co_names: co_names also contains
    # attribute names, which would spuriously capture an unrelated
    # global layer whose name collides with any `obj.attr` access.
    if code is not None:
        g = getattr(raw, "__globals__", {})
        for name in dict.fromkeys(_loaded_global_names(code)):
            if name in g:
                add_container(name, g[name])
    return out


@functools.lru_cache(maxsize=512)
def _loaded_global_names(code):
    """Names a code object LOADs as globals. Cached per code object —
    bytecode is immutable, so only the *bindings* need re-resolution per
    call, never the disassembly."""
    import dis
    names = []
    for ins in dis.get_instructions(code):
        if ins.opname == "LOAD_GLOBAL":
            names.append(ins.argval)
    for const in code.co_consts:
        if hasattr(const, "co_code"):
            names.extend(_loaded_global_names(const))
    return tuple(names)


_to_static_enabled = True
_code_level = 0
_verbosity = 0


class StaticFunction:
    """Compiled callable (ref: ``dy2static/program_translator.py:305``)."""

    def __init__(self, function, input_spec=None, layer: Layer | None = None,
                 build_strategy=None, backend=None, full_graph=True):
        self._orig_fn = function
        self._layer = layer
        self._input_spec = input_spec
        self._jitted = None
        self._closure_param_tensors = None
        self._closure_buffer_tensors = None
        try:
            functools.update_wrapper(self, function)
        except AttributeError:
            pass

    @property
    def layer(self):
        return self._layer

    def _build(self):
        layer = self._layer
        fn = self._orig_fn

        def pure(params, buffers, key, traced, struct, traced_idx, statics,
                 training):
            # rebuild the (args, kwargs) pytree: traced arrays fill the
            # traced slots (rewrapped as Tensors), static leaves fill theirs
            n_leaves = len(traced) + len(statics)
            leaves = [None] * n_leaves
            for i, a in zip(traced_idx, traced):
                leaves[i] = Tensor(a)
            for i, v in statics:
                leaves[i] = v
            args, kwargs = jax.tree_util.tree_unflatten(struct, leaves)
            with _random.trace_key_scope(key):
                if layer is not None:
                    out, new_buffers = functional_call(
                        layer, params, buffers, args, kwargs,
                        training=training, forward_fn=fn)
                else:
                    # swap closure-captured layers' param/buffer arrays so
                    # they trace as program inputs (see
                    # _closure_layer_targets); restore afterwards
                    targets = dict(self._closure_param_tensors or [])
                    btargets = dict(self._closure_buffer_tensors or [])
                    saved = {k: t._data for k, t in targets.items()}
                    bsaved = {k: t._data for k, t in btargets.items()}
                    try:
                        for k, t in targets.items():
                            t._data = params[k]
                        for k, t in btargets.items():
                            t._data = buffers[k]
                        with autograd.functional_guard():
                            out = fn(*args, **kwargs)
                        new_buffers = {k: t._data
                                       for k, t in btargets.items()}
                    finally:
                        for k, t in targets.items():
                            t._data = saved[k]
                        for k, t in btargets.items():
                            t._data = bsaved[k]
            out_arrays = jax.tree_util.tree_map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
            return out_arrays, new_buffers

        self._jitted = jax.jit(
            pure, static_argnames=("struct", "traced_idx", "statics",
                                   "training"))

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            # jit.enable_to_static(False): run the original dygraph code
            # (the reference's debugging fallback); _orig_fn is already
            # bound when wrapping a Layer's forward
            return self._orig_fn(*args, **kwargs)
        if self._jitted is None:
            self._build()
        layer = self._layer
        training = layer.training if layer is not None else False

        leaves, struct = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        traced_idx = []
        traced_vals = []
        tensor_slots = []  # (position in traced list, original Tensor)
        statics = []
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, Tensor):
                tensor_slots.append((len(traced_vals), leaf))
                traced_idx.append(i)
                traced_vals.append(leaf._data)
            elif _is_arraylike(leaf):
                traced_idx.append(i)
                traced_vals.append(jnp.asarray(leaf))
            else:
                try:
                    hash(leaf)
                    statics.append((i, leaf))
                except TypeError:
                    traced_idx.append(i)
                    traced_vals.append(jnp.asarray(leaf))
        traced_idx_t = tuple(traced_idx)
        statics_t = tuple(statics)

        if layer is not None:
            params = dict(layer.named_parameters())
            buffers = dict(layer.named_buffers())
        else:
            params, buffers = {}, {}
            cp, cb, modes = [], [], []
            # re-scan every call: caching the Layer objects would go
            # stale when a captured global/closure layer is REBOUND to a
            # fresh instance (notebook re-init) — the stale object would
            # silently reintroduce the traced-as-constant no-grad bug
            for pref, ly in _closure_layer_targets(self._orig_fn):
                for k, t in dict(ly.named_parameters()).items():
                    params[f"{pref}::{k}"] = t
                    cp.append((f"{pref}::{k}", t))
                for k, t in dict(ly.named_buffers()).items():
                    buffers[f"{pref}::{k}"] = t
                    cb.append((f"{pref}::{k}", t))
                modes.append((pref, ly.training))
            self._closure_param_tensors = cp
            self._closure_buffer_tensors = cb
            # per-layer modes form the static cache key: each layer reads
            # its OWN .training at trace time, so any single flip (bn
            # eval vs dropout train) must retrace, not cache-hit on an
            # aggregate boolean
            training = tuple(modes)
        p_names = sorted(params)
        b_names = sorted(buffers)
        p_tensors = [params[k] for k in p_names]
        b_arrays = {k: buffers[k]._data for k in b_names}
        key = _random.next_key()
        jitted = self._jitted

        def run(p_arrays, traced_list):
            pd = dict(zip(p_names, p_arrays))
            return jitted(pd, b_arrays, key, traced_list, struct,
                          traced_idx_t, statics_t, training)

        grad_tensors = [t for _, t in tensor_slots if not t.stop_gradient]
        needs_grad = (autograd.is_grad_enabled()
                      and not autograd.in_functional_mode()
                      and (any(not p.stop_gradient for p in p_tensors)
                           or bool(grad_tensors)))
        if needs_grad:
            grad_slots = [pos for pos, t in tensor_slots
                          if not t.stop_gradient]

            def for_vjp(p_arrays, *g_args):
                tl = list(traced_vals)
                for pos, a in zip(grad_slots, g_args):
                    tl[pos] = a
                return run(p_arrays, tl)

            (out_arrays, new_buffers), vjp_fn = jax.vjp(
                for_vjp, [p._data for p in p_tensors],
                *[traced_vals[pos] for pos in grad_slots])

            out_leaves, out_struct = jax.tree_util.tree_flatten(out_arrays)
            out_tensors = [Tensor(a, stop_gradient=False) for a in out_leaves]
            nb_zero = jax.tree_util.tree_map(jnp.zeros_like, new_buffers)

            def node_vjp(cots):
                cot_list = list(cots) if isinstance(cots, tuple) else [cots]
                cot_tree = jax.tree_util.tree_unflatten(out_struct, cot_list)
                gp, *gargs = vjp_fn((cot_tree, nb_zero))
                return tuple(gp) + tuple(gargs)

            node_inputs = p_tensors + [t for _, t in tensor_slots
                                       if not t.stop_gradient]
            node = autograd.Node(node_inputs, node_vjp, out_tensors,
                                 name="to_static")
            for i, t in enumerate(out_tensors):
                t._node = node
                t._out_idx = i
            result = jax.tree_util.tree_unflatten(out_struct, out_tensors)
        else:
            out_arrays, new_buffers = run([p._data for p in p_tensors],
                                          traced_vals)
            result = jax.tree_util.tree_map(
                lambda a: Tensor(a) if _is_arraylike(a) else a, out_arrays)

        if new_buffers:
            if layer is not None:
                named_b = dict(layer.named_buffers())
                for k, arr in new_buffers.items():
                    named_b[k]._data = arr
            elif self._closure_buffer_tensors:
                targets = dict(self._closure_buffer_tensors)
                for k, arr in new_buffers.items():
                    targets[k]._data = arr
        return result

    # paddle parity helpers -------------------------------------------------
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._orig_fn)
        except (OSError, TypeError):
            return "<source unavailable>"

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    def rollback(self):
        if self._layer is not None and hasattr(self._layer, "_orig_forward"):
            self._layer.forward = self._layer._orig_forward
        return self._orig_fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """``paddle.jit.to_static`` equivalent (decorator or direct call).

    Accepts a Layer (converts its forward in place and returns the layer),
    a bound method of a Layer, or a plain function.
    """

    def decorate(obj):
        if isinstance(obj, Layer):
            sf = StaticFunction(obj.forward, input_spec, layer=obj,
                                build_strategy=build_strategy)
            obj._orig_forward = obj.forward
            obj.forward = sf
            return obj
        self_layer = getattr(obj, "__self__", None)
        if isinstance(self_layer, Layer):
            return StaticFunction(obj, input_spec, layer=self_layer,
                                  build_strategy=build_strategy)
        return StaticFunction(obj, input_spec, layer=None,
                              build_strategy=build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(function=None):
    return function
