"""Whole-step trace-and-cache capture: the eager fast path.

``@capture_step`` records ONE execution of a user's raw training-loop
body — Layer forward, loss, ``loss.backward()``, ``optimizer.step()`` —
and replays every subsequent call as a single jitted, donation-annotated
pure computation over (params, buffers, opt_state, rng counter, batch).
This is the paper's standalone-executor/dygraph-to-static story for
users who write their own loop instead of ``hapi.Model`` (ref:
``python/paddle/jit/api.py to_static`` + ``fluid/executor.py
_ExecutorCache``): the loop keeps its eager shape, the hardware sees one
XLA program per step.

How the one trace works: the tape stays ON while jax traces the user
function, so ``loss.backward()`` runs the ordinary autograd walk — each
``Node``'s lazy ``jax.vjp`` simply traces into the outer jit.
``optimizer.step()`` is intercepted by a capture hook (see
``Optimizer.step``) that applies the pure ``apply_gradients_tree``
update over the threaded opt-state pytree instead of the eager
per-param jits, so the step counter / lr are runtime arguments, never
baked constants.

Cache key: arg-tree structure + (shape, dtype, stop_gradient) per
tensor leaf + hashable non-tensor leaves + per-layer training mode.
Same shapes → replay with zero retrace (the recompile sentinel stays
quiet); a dtype/shape change compiles exactly one new entry.

Donation safety: at capture time the layer's current arrays are
device-copied into capture-private buffers; only those (and each call's
outputs, which nothing else references) are ever donated. The arrays
the caller held before capturing are never invalidated. Raw ``._data``
references taken BETWEEN captured calls die at the next call — the
hazard tpu-lint TPU011 flags.

Fallback: capture-unsafe code (data-dependent Python control flow, host
syncs like ``float(loss)``) raises a tracer error during the first
trace; the step falls back to plain eager permanently, with a one-shot
diagnostic naming the offending user line. ``PT_CAPTURE=0`` disables
capture globally.
"""
from __future__ import annotations

import functools
import os
import sys
import time
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework import random as _random
from ..nn.layer.layers import Layer
from ..optimizer.optimizer import Optimizer
from ..observability.logs import get_logger
from .api import _closure_layer_targets, _loaded_global_names, _is_arraylike

__all__ = ["capture_step", "CapturedStep"]

logger = get_logger(__name__)

_TRACE_ERRORS = tuple(
    e for e in (
        getattr(jax.errors, n, None)
        for n in ("ConcretizationTypeError", "TracerArrayConversionError",
                  "TracerBoolConversionError", "TracerIntegerConversionError",
                  "UnexpectedTracerError", "NonConcreteBooleanIndexError"))
    if e is not None)

_FALSY = {"0", "false", "no", "off"}


def _capture_enabled():
    return os.environ.get("PT_CAPTURE", "1").strip().lower() not in _FALSY


def _device_copy(a):
    """A fresh device array with the same contents — the donation
    firewall between capture-private state and caller-held arrays."""
    return jnp.array(a, copy=True)


def _closure_optimizers(fn):
    """Optimizer instances reachable from fn's closure / globals /
    bound self — the same discovery rule as ``_closure_layer_targets``
    (jit/api.py): anything not threaded through the trace would bake
    its state as constants."""
    out, seen = [], set()

    def add(val):
        if isinstance(val, Optimizer) and id(val) not in seen:
            seen.add(id(val))
            out.append(val)

    def add_container(val):
        add(val)
        if isinstance(val, (list, tuple)):
            for v in val:
                add(v)
        elif isinstance(val, dict):
            for v in val.values():
                add(v)

    obj = getattr(fn, "__self__", None)
    if obj is not None and hasattr(obj, "__dict__"):
        for v in vars(obj).values():
            add_container(v)
    raw = getattr(fn, "__wrapped__", fn)
    code = getattr(raw, "__code__", None)
    cells = getattr(raw, "__closure__", None) or ()
    names = code.co_freevars if code is not None else ()
    for name, cell in zip(names, cells):
        try:
            add_container(cell.cell_contents)
        except ValueError:
            continue
    if code is not None:
        g = getattr(raw, "__globals__", {})
        for name in dict.fromkeys(_loaded_global_names(code)):
            if name in g:
                add_container(g[name])
    return out


def _tel():
    from ..observability import get_telemetry
    return get_telemetry()


def _tracer():
    from ..observability.trace import get_tracer
    return get_tracer()


class _LiveState:
    """Capture-private mutable state shared by all signature entries of
    one CapturedStep: the donated param/buffer/opt-state arrays plus the
    live Tensor objects they shadow."""

    __slots__ = ("layers", "param_tensors", "buffer_tensors", "params",
                 "buffers", "opts", "opt_param_names", "opt_states",
                 "rng_base", "rng_ctr")


class _Entry:
    __slots__ = ("jitted", "struct", "traced_idx", "sg_flags", "statics",
                 "n_leaves", "sig", "name", "ran", "flops", "fusion",
                 "memory", "monitored", "monitor_names", "sdc",
                 "sdc_names", "pure", "audit")


class CapturedStep:
    """One captured training-step callable (see module docstring)."""

    def __init__(self, fn):
        self._fn = fn
        self._cache = {}
        self._state = None
        self._fallback_reason = None
        self.stats = {"hits": 0, "misses": 0, "compiles": 0,
                      "fallback": None, "fusion_rewrites": 0,
                      "fusion_patterns": {}}
        try:
            functools.update_wrapper(self, fn)
        except AttributeError:
            pass

    # -- public knobs -------------------------------------------------------
    @property
    def fallback_reason(self):
        return self._fallback_reason

    def reset(self):
        """Drop every compiled entry and the private state (tests /
        notebook re-init). Layer tensors keep their current arrays."""
        self._cache.clear()
        self._state = None
        self._fallback_reason = None
        self.stats["fallback"] = None

    # -- dispatch -----------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if self._fallback_reason is not None or not _capture_enabled():
            return self._fn(*args, **kwargs)
        # ONE flatten per call feeds signature, arg screening and replay
        leaves, struct = self._flatten(args, kwargs)
        try:
            sig = self._signature(leaves, struct)
        except TypeError:  # unhashable static leaf
            sig = None
        if sig is None or any(isinstance(l, (Layer, Optimizer))
                              for l in leaves):
            self._fall_back("unsupported_args", None)
            return self._fn(*args, **kwargs)
        entry = self._cache.get(sig)
        tel = _tel()
        if entry is not None:
            self.stats["hits"] += 1
            tel.capture_cache_hit()
            return self._replay(entry, leaves)
        reason = "first_trace" if not self._cache else "signature_change"
        self.stats["misses"] += 1
        tel.capture_cache_miss(reason)
        try:
            # jax.jit is lazy — the trace (where capture-unsafe code
            # raises) happens inside the first replay, so it is covered
            # by this except too
            entry = self._compile(args, kwargs, sig)
            result = self._replay(entry, leaves)
        except _TRACE_ERRORS as e:
            self._fall_back("capture_unsafe", e)
            return self._fn(*args, **kwargs)
        self._cache[sig] = entry
        return result

    # -- signature ----------------------------------------------------------
    def _flatten(self, args, kwargs):
        return jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))

    def _signature(self, leaves, struct):
        key = [struct]
        for leaf in leaves:
            if isinstance(leaf, Tensor):
                d = leaf._data
                # dtype objects hash directly; str() on them is the
                # single hottest line of the naive key (numpy renders
                # the name on every call)
                key.append(("t", d.shape, d.dtype, leaf.stop_gradient))
            elif _is_arraylike(leaf):
                key.append(("a", np.shape(leaf), np.asarray(leaf).dtype))
            else:
                key.append(("s", leaf))
        # training-mode flips (dropout/bn) are baked into a trace, so
        # they key the cache; the scan also catches a rebound global
        # layer (fresh object → fresh ids → honest retrace)
        for pref, ly in _closure_layer_targets(self._fn):
            key.append((id(ly), ly.training))
        return hash(tuple(key))

    # -- capture ------------------------------------------------------------
    def _build_state(self):
        st = _LiveState()
        st.layers = _closure_layer_targets(self._fn)
        st.param_tensors, st.buffer_tensors = {}, {}
        st.params, st.buffers = {}, {}
        for pref, ly in st.layers:
            for k, t in dict(ly.named_parameters()).items():
                name = f"{pref}::{k}"
                if name not in st.param_tensors:
                    st.param_tensors[name] = t
                    st.params[name] = _device_copy(t._data)
            for k, t in dict(ly.named_buffers()).items():
                name = f"{pref}::{k}"
                if name not in st.buffer_tensors:
                    st.buffer_tensors[name] = t
                    st.buffers[name] = _device_copy(t._data)
        st.opts = _closure_optimizers(self._fn)
        by_id = {id(t): n for n, t in st.param_tensors.items()}
        st.opt_param_names, st.opt_states = [], []
        for oi, opt in enumerate(st.opts):
            onames = []
            for p in opt._parameter_list:
                name = by_id.get(id(p))
                if name is None:  # bare Parameter outside any found Layer
                    name = f"opt{oi}::{p.name}"
                    st.param_tensors[name] = p
                    st.params[name] = _device_copy(p._data)
                    by_id[id(p)] = name
                onames.append(name)
            state = opt.init_state_tree({n: st.params[n] for n in onames})
            # seed from live eager accumulators so capture mid-run
            # continues the same trajectory
            for n in onames:
                pname = st.param_tensors[n].name
                for slot in opt._state_slots:
                    cur = opt._accumulators[slot].get(pname)
                    if cur is not None:
                        state["slots"][slot][n] = _device_copy(cur)
                m = opt._master_weights.get(pname)
                if m is not None:
                    state["master"][n] = _device_copy(m)
            state["step"] = jnp.asarray(opt._global_step, jnp.int32)
            st.opt_param_names.append(onames)
            st.opt_states.append(state)
        # the capture's own key chain: a base key closed over as a
        # program constant plus a host-side int counter folded in INSIDE
        # the compiled program. Host-side fold_in costs ~0.5ms/call, and
        # a typed key as a jit *argument* keeps pjit off its C++ fast
        # dispatch path (~70µs/call) — the counter form costs ~6µs
        st.rng_base = _random.next_key()
        st.rng_ctr = 0
        # census attribution: hand the memory monitor a weakly-held
        # view of the capture-private state so live_arrays() bytes
        # resolve to parameter paths (the enable decision is baked at
        # build time, like the numerics sentinel)
        from ..observability import memory as _memory
        if _memory.get_memory_monitor().enabled:
            _memory.get_memory_monitor().register_provider(
                self._memory_named)
        return st

    def _memory_named(self):
        """Attribution view for the memory census/postmortem: every
        capture-private array by qualified path (``param::<path>``,
        ``buffer::<path>``, ``opt<i>::<slot>::<path>``)."""
        st = self._state
        if st is None:
            return {}
        named = {}
        for n, a in st.params.items():
            named[f"param::{n}"] = a
        for n, a in st.buffers.items():
            named[f"buffer::{n}"] = a
        for oi, state in enumerate(st.opt_states):
            for slot, d in state.get("slots", {}).items():
                for n, a in d.items():
                    named[f"opt{oi}::{slot}::{n}"] = a
            for n, a in state.get("master", {}).items():
                named[f"opt{oi}::master::{n}"] = a
        return named

    def _book_oom(self, entry, exc):
        """RESOURCE_EXHAUSTED intercept: pin the memory postmortem
        (census + footprints + watermark history) into the flight
        recorder before the error propagates — the same trip path the
        numerics sentinels use. Never raises; the caller re-raises the
        original error."""
        try:
            from ..observability import memory as _memory
            if not _memory.is_oom_error(exc):
                return
            _memory.oom_postmortem(program=entry.name, exc=exc,
                                   extra_named=self._memory_named())
        except Exception:
            pass

    def _compile(self, args, kwargs, sig):
        if self._state is None:
            self._state = self._build_state()
        st = self._state
        fn = self._fn
        leaves, struct = self._flatten(args, kwargs)
        traced_idx, sg_flags, statics = [], [], []
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, Tensor):
                traced_idx.append(i)
                sg_flags.append(leaf.stop_gradient)
            elif _is_arraylike(leaf):
                traced_idx.append(i)
                sg_flags.append(True)
            else:
                statics.append((i, leaf))
        n_leaves = len(leaves)
        p_tensors, b_tensors, opts = st.param_tensors, st.buffer_tensors, \
            st.opts
        opt_param_names = st.opt_param_names
        rng_base = st.rng_base
        # numerics sentinel: the enable decision is baked per entry at
        # trace time, so a monitored step carries its health outputs in
        # the SAME program — still exactly one compile per signature
        from ..observability import numerics as _numerics
        mon = _numerics.get_monitor()
        mon = mon if mon.enabled else None
        mon_box = []  # filled with the tensor-name tuple during trace
        # SDC sentry: same per-entry bake as the numerics sentinel —
        # the replica fingerprint vector rides the same program
        from ..observability import sdc as _sdc
        smon = _sdc.get_monitor()
        smon = smon if smon.enabled else None
        sdc_box = []  # filled with the fingerprint-name tuple during trace

        def pure(params, buffers, opt_states, ctr, lrs, traced):
            key = jax.random.fold_in(rng_base, ctr)
            new_opt_states = list(opt_states)
            mon_grads = {}

            def mk_hook(oi):
                opt, onames = opts[oi], opt_param_names[oi]

                def hook(_o):
                    cur_params = {n: p_tensors[n]._data for n in onames}
                    grads = {}
                    for n in onames:
                        t = p_tensors[n]
                        if not t.stop_gradient and t._grad is not None:
                            grads[n] = t._grad._data
                    if mon is not None:
                        mon_grads.update(grads)
                    new_p, new_s = opt.apply_gradients_tree(
                        cur_params, grads, new_opt_states[oi], lr=lrs[oi])
                    for n, arr in new_p.items():
                        p_tensors[n]._data = arr
                    new_opt_states[oi] = new_s
                return hook

            saved = [(t, t._data, t._grad, t._node)
                     for t in list(p_tensors.values())
                     + list(b_tensors.values())]
            try:
                for name, t in p_tensors.items():
                    t._data = params[name]
                    t._grad = None
                for name, t in b_tensors.items():
                    t._data = buffers[name]
                for oi, opt in enumerate(opts):
                    opt._capture_hook = mk_hook(oi)
                lvs = [None] * n_leaves
                for i, a, sg in zip(traced_idx, traced, sg_flags):
                    tt = Tensor(a)
                    tt.stop_gradient = sg
                    lvs[i] = tt
                for i, v in statics:
                    lvs[i] = v
                cargs, ckwargs = jax.tree_util.tree_unflatten(struct, lvs)
                with _random.trace_key_scope(key):
                    out = fn(*cargs, **ckwargs)
                out_arrays = jax.tree_util.tree_map(
                    lambda t: t._data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
                new_params = {n: t._data for n, t in p_tensors.items()}
                new_buffers = {n: t._data for n, t in b_tensors.items()}
                ret = [out_arrays, new_params, new_buffers,
                       new_opt_states]
                if mon is not None:
                    # first scalar inexact output is treated as the loss
                    loss = None
                    for leaf in jax.tree_util.tree_leaves(out_arrays):
                        if (hasattr(leaf, "dtype")
                                and hasattr(leaf, "size")
                                and leaf.size == 1
                                and jnp.issubdtype(leaf.dtype,
                                                   jnp.inexact)):
                            loss = leaf
                            break
                    # flag the UPDATED parameters, not the raw grads:
                    # the new params are already materialized program
                    # outputs, so their per-tensor reductions extend no
                    # intermediate lifetimes (grad-side reductions
                    # measurably inhibit XLA's backward/update fusion),
                    # a non-finite grad corrupts its param in this same
                    # step (same detection latency, same parameter-path
                    # naming), and state corruption — what persists
                    # into every later step — is the thing worth
                    # naming. The explosion detector still watches the
                    # true grad norm via norm_over.
                    monitored = {n: new_params[n] for n in mon_grads}
                    mnames, health = _numerics.health_outputs(
                        monitored, loss=loss, with_stats=mon.stats_on,
                        norm_over=mon_grads)
                    mon_box[:] = [mnames]
                    ret.append(health)
                if smon is not None:
                    # replica fingerprints cover the persistent state a
                    # flipped bit would poison: every updated param plus
                    # every optimizer slot / master weight — all already
                    # materialized program outputs, so the digests cost
                    # one fused reduction each and extend no lifetimes
                    fp_named = {f"param::{n}": a
                                for n, a in new_params.items()}
                    for oi, s in enumerate(new_opt_states):
                        if not isinstance(s, dict):
                            continue
                        for slot, per in (s.get("slots") or {}).items():
                            for n, a in per.items():
                                fp_named[f"opt{oi}::{slot}::{n}"] = a
                        for n, a in (s.get("master") or {}).items():
                            fp_named[f"opt{oi}::master::{n}"] = a
                    snames, fp = _sdc.fingerprint_outputs(fp_named)
                    sdc_box[:] = [snames]
                    ret.append(fp)
                return tuple(ret)
            finally:
                for t, d, g, nd in saved:
                    t._data, t._grad, t._node = d, g, nd
                for opt in opts:
                    opt._capture_hook = None

        fname = getattr(fn, "__name__", "fn")
        pure.__name__ = f"captured_step({fname})"
        pure.__qualname__ = pure.__name__

        # graph-level fusion: rewrite matched clusters (residual+LN,
        # LN+matmul, attention block, matmul+bias+gelu) to block-fused
        # kernels at trace time, before XLA ever sees the step. The wrap
        # is a transparent passthrough when PT_FUSION_PASS=0 or nothing
        # matches.
        from ..ops import fusion_pass as _fusion

        entry = _Entry()
        # the UN-wrapped pure fn is kept for the graph auditor: its
        # pre-fusion jaxpr is exactly what the fusion pass matched, so
        # the missed-fusion cross-check compares like with like
        entry.pure = pure
        entry.audit = None
        entry.jitted = jax.jit(_fusion.wrap(pure), donate_argnums=(0, 1, 2))
        entry.struct = struct
        entry.traced_idx = tuple(traced_idx)
        entry.sg_flags = tuple(sg_flags)
        entry.statics = tuple(statics)
        entry.n_leaves = n_leaves
        entry.sig = sig
        entry.name = pure.__name__
        entry.ran = False
        entry.flops = None
        entry.fusion = None
        entry.memory = None
        entry.monitored = mon is not None
        entry.monitor_names = mon_box  # resolved after the first trace
        entry.sdc = smon is not None
        entry.sdc_names = sdc_box      # resolved after the first trace
        return entry

    # -- replay -------------------------------------------------------------
    def _replay(self, entry, leaves):
        st = self._state
        traced = [None] * len(entry.traced_idx)
        for j, i in enumerate(entry.traced_idx):
            leaf = leaves[i]
            traced[j] = leaf._data if isinstance(leaf, Tensor) \
                else jnp.asarray(leaf)
        # plain floats: jit lifts them to weak-f32 runtime args, so an
        # lr-schedule change never retraces (train_step.py pattern)
        lrs = [float(opt.get_lr()) for opt in st.opts]
        call = entry.jitted
        tr = _tracer()
        was_compile = not entry.ran
        if not entry.ran:
            if tr.enabled and entry.flops is None:
                # analytic MFU source: cost_analysis() at compile time,
                # while the donated input arrays are still live. The AOT
                # lower+compile is redundant with the call below but its
                # XLA compile is cache-shared, and it only happens once
                # per signature — the replay hot path never pays it.
                from ..observability.trace import program_flops
                entry.flops = program_flops(
                    call, st.params, st.buffers, st.opt_states, st.rng_ctr,
                    lrs, traced)
                if entry.flops:
                    tr.record_program_flops(entry.name, entry.flops)
            from ..observability import memory as _memory
            _mm = _memory.get_memory_monitor()
            if _mm.enabled and entry.memory is None:
                # compile-time footprint + pre-flight fit check:
                # memory_analysis() harvested beside the FLOPs, from
                # the same cache-shared AOT compile, BEFORE the first
                # replay below can discover an unfit program as a raw
                # RESOURCE_EXHAUSTED
                entry.memory = _mm.harvest_program(
                    entry.name, call, st.params, st.buffers,
                    st.opt_states, st.rng_ctr, lrs, traced)
            from ..ops import fusion_pass as _fusion
            fusion_before = _fusion.summary()["rewrites"]
            with warnings.catch_warnings():
                # backends without donation (cpu) warn once at compile;
                # the annotation is still correct where it counts
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                t0 = time.perf_counter_ns()
                try:
                    outs = call(st.params, st.buffers, st.opt_states,
                                st.rng_ctr, lrs, traced)
                except Exception as e:
                    self._book_oom(entry, e)
                    raise
            entry.ran = True  # only after the trace actually succeeded
            # the trace just happened inside that call: the fusion-pass
            # rewrite delta is this entry's pattern census (part of the
            # capture contract surfaced by bench_eager)
            fusion_after = _fusion.summary()["rewrites"]
            entry.fusion = {
                k: fusion_after.get(k, 0) - fusion_before.get(k, 0)
                for k in fusion_after
                if fusion_after.get(k, 0) > fusion_before.get(k, 0)}
            for k, n in entry.fusion.items():
                self.stats["fusion_patterns"][k] = \
                    self.stats["fusion_patterns"].get(k, 0) + n
                self.stats["fusion_rewrites"] += n
            self.stats["compiles"] += 1
            tel = _tel()
            if not tel._watcher.installed:
                # feed the recompile sentinel directly when jax's compile
                # log isn't being watched (watcher installed → the log
                # filter records this compile; both would double-count)
                tel.record_compile(entry.name, f"sig={entry.sig}")
            if entry.audit is None:
                # graph audit (tools/audit): static findings over the
                # pre-fusion step jaxpr, harvested once per signature
                # in the same compile-time window as the FLOPs/memory
                # passes above — the replay hot path never pays it
                from ..tools.audit import runtime as _audit_rt
                if _audit_rt.audit_enabled():
                    entry.audit = _audit_rt.audit_captured_step(
                        entry, st.params, st.buffers, st.opt_states,
                        st.rng_ctr, lrs, traced)
                else:
                    entry.audit = ()
        else:
            t0 = time.perf_counter_ns()
            try:
                outs = call(st.params, st.buffers, st.opt_states,
                            st.rng_ctr, lrs, traced)
            except Exception as e:
                self._book_oom(entry, e)
                raise
        if tr.enabled:
            # dispatch-side span: async under jax, so this is dispatch +
            # any implicit materialization, never a forced device sync.
            # The first call is dominated by trace+compile and is billed
            # as such — the goodput ledger classifies it as overhead,
            # not productive compute.
            if was_compile:
                tr.record_span(f"compile:{entry.name}", "host", t0,
                               time.perf_counter_ns())
            else:
                tr.record_span(entry.name, "compute", t0,
                               time.perf_counter_ns())
        step_idx = st.rng_ctr
        st.rng_ctr += 1
        outs = list(outs)
        fp = outs.pop() if entry.sdc else None
        health = outs.pop() if entry.monitored else None
        out_arrays, st.params, st.buffers, st.opt_states = outs
        for name, t in st.param_tensors.items():
            t._data = st.params[name]
        for name, t in st.buffer_tensors.items():
            t._data = st.buffers[name]
        for oi, opt in enumerate(st.opts):
            opt._global_step += 1
            s = st.opt_states[oi]
            for n in st.opt_param_names[oi]:
                pname = st.param_tensors[n].name
                for slot in opt._state_slots:
                    opt._accumulators[slot][pname] = s["slots"][slot][n]
                if n in s["master"]:
                    opt._master_weights[pname] = s["master"][n]
        # watermark timeline: step-boundary allocator sample. sys.modules-
        # gated like the telemetry hooks — a run that never imported the
        # memory module pays one dict lookup here.
        mem_mod = sys.modules.get("paddle_tpu.observability.memory")
        if mem_mod is not None:
            mm = mem_mod.current_memory_monitor()
            if mm is not None and mm.enabled:
                mm.on_step(step_idx)
        if entry.monitored:
            # hand the (tiny) health arrays to the monitor; it reads
            # the previous packet at cadence boundaries, so this never
            # blocks the step. May raise NumericsHaltError (after the
            # state writeback above) when PT_NUMERICS_HALT=1.
            from ..observability import numerics as _numerics
            m = _numerics.current_monitor()
            if m is not None and entry.monitor_names:
                m.watch(step_idx, entry.monitor_names[0], health)
        if entry.sdc:
            # same discipline for the SDC fingerprint packet: held one
            # dispatch behind, voted on at cadence boundaries. May
            # raise SdcHaltError when consensus fingers this rank.
            from ..observability import sdc as _sdc
            sm = _sdc.current_monitor()
            if sm is not None and entry.sdc_names:
                sm.watch(step_idx, entry.sdc_names[0], fp)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a) if _is_arraylike(a) else a, out_arrays)

    # -- fallback -----------------------------------------------------------
    def _fall_back(self, reason, exc):
        self._fallback_reason = reason
        self.stats["fallback"] = reason
        _tel().capture_cache_miss(reason)
        fname = getattr(self._fn, "__name__", "fn")
        where = self._user_line(exc)
        detail = f": {type(exc).__name__}: {str(exc)[:200]}" if exc else ""
        logger.warning(
            "capture_step(%s): falling back to eager (%s)%s%s — the step "
            "will run un-jitted; remove the host sync / data-dependent "
            "branch (or set PT_CAPTURE=0 to silence)",
            fname, reason, f" at {where}" if where else "", detail)

    def _user_line(self, exc):
        if exc is None:
            return None
        code = getattr(getattr(self._fn, "__wrapped__", self._fn),
                       "__code__", None)
        if code is None:
            return None
        tb, best = exc.__traceback__, None
        while tb is not None:
            if tb.tb_frame.f_code.co_filename == code.co_filename:
                best = f"{code.co_filename}:{tb.tb_lineno}"
            tb = tb.tb_next
        return best


def capture_step(fn=None):
    """Decorator: trace-and-cache a whole training-step function.

    ::

        @paddle_tpu.jit.capture_step
        def step(x, y):
            loss = mse(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

    The model/optimizer must be reachable from the function's closure,
    globals, or bound ``self`` (same rule as ``to_static`` on plain
    functions). See the module docstring for cache-key and fallback
    semantics.
    """
    if fn is None:
        return capture_step
    return CapturedStep(fn)
