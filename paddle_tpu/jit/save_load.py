"""jit.save / jit.load (ref: ``python/paddle/jit/api.py save/load`` and the
C++ serializer ``paddle/fluid/jit/``).

TPU-native format: StableHLO via ``jax.export`` (+ a .pdiparams-style npz of
parameters and a JSON manifest). The exported artifact is hardware-portable
and re-loadable without the python model class — same contract as the
reference's saved inference programs.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.export  # noqa: F401  (binds the submodule attr; not re-exported on older jax)
import jax.numpy as jnp

from ..tensor import Tensor
from ..nn.layer.layers import Layer
from .api import StaticFunction, InputSpec, to_static, functional_call

__all__ = ["save", "load", "TranslatedLayer"]


def _spec_to_aval(spec: InputSpec):
    from ..framework.dtype import to_jax_dtype
    shape = tuple(1 if s is None or s < 0 else int(s) for s in spec.shape)
    return jax.ShapeDtypeStruct(shape, to_jax_dtype(spec.dtype))


def save(layer, path, input_spec=None, **configs):
    """Serialize layer to `path` + {.json, .npz, .stablehlo}."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, StaticFunction):
        sf = layer
        layer = sf._layer
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer (or to_static Layer)")

    was_training = layer.training
    layer.eval()
    try:
        params = {k: np.asarray(p._data) for k, p in layer.named_parameters()}
        buffers = {k: np.asarray(b._data) for k, b in layer.named_buffers()}

        if input_spec is None:
            raise ValueError(
                "jit.save requires input_spec (XLA export needs concrete "
                "shapes); pass e.g. input_spec=[InputSpec([1, 3, 224, 224])]")
        specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
                 for s in input_spec]
        avals = [_spec_to_aval(s) for s in specs]

        fwd = getattr(layer, "_orig_forward", layer.forward)
        if isinstance(fwd, StaticFunction):
            fwd = fwd._orig_fn

        def pure(p, b, *inputs):
            args = [Tensor(x) for x in inputs]
            out, _ = functional_call(layer, p, b, tuple(args),
                                     training=False, forward_fn=fwd)
            return jax.tree_util.tree_map(
                lambda t: t._data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        p_tree = {k: jnp.asarray(v) for k, v in params.items()}
        b_tree = {k: jnp.asarray(v) for k, v in buffers.items()}
        exported = jax.export.export(jax.jit(pure))(p_tree, b_tree, *avals)
        blob = exported.serialize()

        with open(path + ".stablehlo", "wb") as f:
            f.write(blob)
        np.savez(path + ".pdiparams.npz", **params,
                 **{f"__buffer__{k}": v for k, v in buffers.items()})
        manifest = {
            "format": "paddle_tpu.jit.v1",
            "input_specs": [{"shape": list(s.shape), "dtype": str(s.dtype)}
                            for s in specs],
            "param_names": sorted(params),
            "buffer_names": sorted(buffers),
        }
        with open(path + ".json", "w") as f:
            json.dump(manifest, f, indent=2)
    finally:
        if was_training:
            layer.train()
    return path


class TranslatedLayer(Layer):
    """Loaded inference layer (ref: ``translated_layer.py TranslatedLayer``)."""

    def __init__(self, exported, params, buffers, manifest):
        super().__init__()
        self._exported = exported
        self._manifest = manifest
        self._param_arrays = {k: jnp.asarray(v) for k, v in params.items()}
        self._buffer_arrays = {k: jnp.asarray(v) for k, v in buffers.items()}
        from ..tensor import Parameter
        for k, v in self._param_arrays.items():
            self.add_parameter(k.replace(".", "__"), Parameter(v,
                                                               trainable=False))

    def forward(self, *inputs):
        arrays = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                  for x in inputs]
        out = self._exported.call(self._param_arrays, self._buffer_arrays,
                                  *arrays)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a) if isinstance(a, jax.Array) else a, out)


def load(path, **configs):
    with open(path + ".stablehlo", "rb") as f:
        exported = jax.export.deserialize(f.read())
    data = np.load(path + ".pdiparams.npz")
    params, buffers = {}, {}
    for k in data.files:
        if k.startswith("__buffer__"):
            buffers[k[len("__buffer__"):]] = data[k]
        else:
            params[k] = data[k]
    with open(path + ".json") as f:
        manifest = json.load(f)
    return TranslatedLayer(exported, params, buffers, manifest)
