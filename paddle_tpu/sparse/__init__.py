"""``paddle.sparse`` — sparse tensors and ops.

TPU-native re-design of the reference sparse stack
(``python/paddle/sparse/`` API over ``phi::SparseCooTensor`` /
``SparseCsrTensor`` C++ tensors and cuSPARSE kernels,
``paddle/phi/kernels/sparse/``):

 - storage: ``jax.experimental.sparse`` BCOO/BCSR — the XLA-era sparse
   format (indices+values as dense arrays, ops lowered to gather/scatter/
   segment-sum which XLA can fuse and shard).
 - ``SparseCooTensor``/``SparseCsrTensor`` here are thin wrappers carrying
   the paddle API (``.indices()``, ``.values()``, ``.to_dense()``...).
 - elementwise zero-preserving ops map over ``values`` only; matmul rides
   ``bcoo_dot_general`` (TPU-compatible: no cuSPARSE analog needed).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..tensor import Tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "coalesce",
    # unary
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh", "sqrt",
    "square", "log1p", "abs", "pow", "cast", "neg", "deg2rad", "rad2deg",
    "expm1", "isnan",
    # binary / multiary
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "addmm", "mv", "transpose", "sum", "reshape", "slice",
    "pca_lowrank",
    "nn",
]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor (ref: ``paddle/phi/core/sparse_coo_tensor.h``)."""

    format = "coo"

    def __init__(self, bcoo: jsparse.BCOO, values_t=None):
        self._bcoo = bcoo
        # optional tape-linked values Tensor (set by sparse.nn ops so
        # gradients flow through sparse layers like dense ones)
        self._values_t = values_t

    # -- paddle surface -----------------------------------------------------
    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle: [sparse_dim, nnz]

    def values(self):
        if self._values_t is not None:
            return self._values_t
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        coo = self.coalesce_()._bcoo
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(coo))

    def coalesce_(self):
        return SparseCooTensor(
            jsparse.bcoo_sum_duplicates(self._bcoo))

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return self._bcoo.nse

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    # convenience arithmetic
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor:
    """CSR sparse tensor (ref: ``paddle/phi/core/sparse_csr_tensor.h``)."""

    format = "csr"

    def __init__(self, bcsr: jsparse.BCSR):
        self._bcsr = bcsr

    def crows(self):
        return Tensor(self._bcsr.indptr)

    def cols(self):
        return Tensor(self._bcsr.indices)

    def values(self):
        return Tensor(self._bcsr.data)

    def to_dense(self):
        return Tensor(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcsr.to_bcoo())

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    @property
    def nnz(self):
        return self._bcsr.nse

    def numpy(self):
        return np.asarray(self._bcsr.todense())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """``paddle.sparse.sparse_coo_tensor`` (indices: [sparse_dim, nnz])."""
    idx = _data(indices).astype(jnp.int32).T  # jax BCOO: [nnz, sparse_dim]
    # keep the tape link only when the caller did NOT ask for a detached
    # tensor (explicit stop_gradient=False, the paddle contract) — or
    # when the values are themselves a recorded op output (sparse.nn
    # internals thread gradients through here)
    is_op_output = (isinstance(values, Tensor)
                    and not values.stop_gradient
                    and values._node is not None)
    keep_link = (isinstance(values, Tensor) and dtype is None
                 and (not stop_gradient or is_op_output))
    vals_t = values if keep_link else None
    vals = _data(values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype
        vals = vals.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=0))
        shape = shape + vals.shape[1:]
    bcoo = jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo, values_t=vals_t)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """``paddle.sparse.sparse_csr_tensor``."""
    vals = _data(values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype
        vals = vals.astype(to_jax_dtype(dtype))
    bcsr = jsparse.BCSR(
        (vals, _data(cols).astype(jnp.int32),
         _data(crows).astype(jnp.int32)),
        shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(bcsr)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def coalesce(x):
    return x.coalesce_()


def _coo(x) -> jsparse.BCOO:
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x._bcsr.to_bcoo()
    raise TypeError(f"expected sparse tensor, got {type(x)}")


def _same_kind(x, bcoo):
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            jsparse.bcoo_sum_duplicates(bcoo)))
    return SparseCooTensor(bcoo)


# -- unary (zero-preserving: map over values) --------------------------------
def _unary(fn):
    def op(x, name=None):
        if isinstance(x, SparseCsrTensor):
            b = x._bcsr
            return SparseCsrTensor(
                jsparse.BCSR((fn(b.data), b.indices, b.indptr),
                             shape=b.shape))
        b = _coo(x)
        return SparseCooTensor(
            jsparse.BCOO((fn(b.data), b.indices), shape=b.shape))
    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
expm1 = _unary(jnp.expm1)
isnan = _unary(jnp.isnan)


def pow(x, factor, name=None):
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import to_jax_dtype
    b = _coo(x)
    vals = b.data if value_dtype is None else b.data.astype(
        to_jax_dtype(value_dtype))
    idx = b.indices if index_dtype is None else b.indices.astype(
        to_jax_dtype(index_dtype))
    return _same_kind(x, jsparse.BCOO((vals, idx), shape=b.shape))


# -- binary ------------------------------------------------------------------
def _union_binary(x, y, fn):
    """sparse op sparse over the union of patterns (concat + dedup)."""
    a, b = _coo(x), _coo(y)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    data = jnp.concatenate([a.data, fn(b.data)])
    idx = jnp.concatenate([a.indices, b.indices], axis=0)
    out = jsparse.bcoo_sum_duplicates(
        jsparse.BCOO((data, idx), shape=a.shape))
    return _same_kind(x, out)


def add(x, y, name=None):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return _union_binary(x, y, lambda v: v)
    return Tensor(_coo(x).todense() + _data(y))


def subtract(x, y, name=None):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return _union_binary(x, y, jnp.negative)
    return Tensor(_coo(x).todense() - _data(y))


def multiply(x, y, name=None):
    if isinstance(y, (int, float)):
        return _unary(lambda v: v * y)(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        # same-pattern fast path, else dense fallback (dedup BEFORE the
        # shape comparison — duplicates change nse)
        a = jsparse.bcoo_sum_duplicates(_coo(x))
        b = jsparse.bcoo_sum_duplicates(_coo(y))
        if a.indices.shape == b.indices.shape and \
                bool(jnp.all(a.indices == b.indices)):
            return _same_kind(x, jsparse.BCOO((a.data * b.data, a.indices),
                                              shape=a.shape))
        return Tensor(a.todense() * b.todense())
    # sparse * dense: gather dense at indices
    a = jsparse.bcoo_sum_duplicates(_coo(x))
    d = _data(y)
    gathered = d[tuple(a.indices[:, i] for i in range(a.indices.shape[1]))]
    return _same_kind(x, jsparse.BCOO((a.data * gathered, a.indices),
                                      shape=a.shape))


def divide(x, y, name=None):
    if isinstance(y, (int, float)):
        return _unary(lambda v: v / y)(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return Tensor(_coo(x).todense() / _coo(y).todense())
    a = jsparse.bcoo_sum_duplicates(_coo(x))
    d = _data(y)
    gathered = d[tuple(a.indices[:, i] for i in range(a.indices.shape[1]))]
    return _same_kind(x, jsparse.BCOO((a.data / gathered, a.indices),
                                      shape=a.shape))


def matmul(x, y, name=None):
    """sparse @ dense -> dense (ref: sparse matmul via cuSPARSE; here
    ``bcoo_dot_general`` lowers to XLA gather/segment-sum)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        a = _coo(x)
        out = jsparse.bcoo_dot_general(
            a, _data(y), dimension_numbers=(([a.ndim - 1], [0]), ([], [])))
        return Tensor(out)
    raise TypeError("matmul expects a sparse lhs")


def mv(x, vec, name=None):
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return Tensor(beta * _data(input) + alpha * matmul(x, y)._data)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated only at ``mask``'s sparsity pattern."""
    a, b = _data(x), _data(y)
    m = jsparse.bcoo_sum_duplicates(_coo(mask))
    rows, cols = m.indices[:, 0], m.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))


def transpose(x, perm, name=None):
    return _same_kind(x, jsparse.bcoo_transpose(
        _coo(x), permutation=tuple(perm)))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = _coo(x).todense().sum(axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype
        d = d.astype(to_jax_dtype(dtype))
    return Tensor(d)


def reshape(x, shape, name=None):
    return _same_kind(x, jsparse.bcoo_reshape(
        jsparse.bcoo_sum_duplicates(_coo(x)),
        new_sizes=tuple(int(s) for s in shape)))


def slice(x, axes, starts, ends, name=None):
    b = jsparse.bcoo_sum_duplicates(_coo(x))
    start = [0] * b.ndim
    limit = list(b.shape)
    for ax, s, e in zip(axes, starts, ends):
        start[ax] = int(s) if s >= 0 else int(s) + b.shape[ax]
        limit[ax] = min(int(e) if e >= 0 else int(e) + b.shape[ax],
                        b.shape[ax])
    return _same_kind(x, jsparse.bcoo_slice(b, start_indices=start,
                                            limit_indices=limit))


from . import nn  # noqa: E402,F401


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA of a sparse matrix (ref:
    ``python/paddle/sparse/unary.py:956 pca_lowrank``).

    Halko-style randomized range finding; every product against X rides
    the sparse ``bcoo_dot_general`` path, so X is never densified.
    Centering uses the rank-one correction (X - 1·c) @ W =
    X @ W - 1·(c @ W) — the same trick the reference uses so sparse
    inputs stay sparse. Returns dense (U, S, V) Tensors.
    """
    if not isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("sparse.pca_lowrank expects a sparse COO/CSR tensor")
    a = _coo(x)
    if a.ndim != 2:
        raise ValueError("pca_lowrank expects a 2-D matrix")
    n, m = a.shape
    if q is None:
        q = min(6, n, m)
    if not 0 < q <= min(n, m):
        raise ValueError(f"q must be in (0, min(N, M)={min(n, m)}]; got {q}")
    from ..framework import random as _random
    key = _random.next_key()
    if jnp.issubdtype(a.dtype, jnp.complexfloating):
        raise TypeError("pca_lowrank does not support complex input "
                        "(reference supports float32/float64 only)")
    dt = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32
    if a.dtype != dt:  # int input: cast once so bcoo_dot_general agrees
        a = jsparse.BCOO((a.data.astype(dt), a.indices), shape=a.shape)

    def smm(w):  # X @ w without densifying X
        return jsparse.bcoo_dot_general(
            a, w, dimension_numbers=(([1], [0]), ([], [])))

    def smm_t(w):  # X^T @ w: contract X's rows against w's rows -> (M, q)
        return jsparse.bcoo_dot_general(
            a, w, dimension_numbers=(([0], [0]), ([], [])))

    if center:
        ones = jnp.ones((n, 1), dt)
        c = (jsparse.bcoo_dot_general(
            a, jnp.ones((n,), dt),
            dimension_numbers=(([0], [0]), ([], []))) / n)[None, :]  # (1, M)

        def cmm(w):        # (X - 1 c) @ w
            return smm(w) - ones @ (c @ w)

        def cmm_t(w):      # (X - 1 c)^T @ w
            return smm_t(w) - c.T @ (ones.T @ w)
    else:
        cmm, cmm_t = smm, smm_t

    p = min(q + 6, n, m)  # oversampled range dim; truncated back to q
    omega = jax.random.normal(key, (m, p), dt)
    y = cmm(omega)
    qmat, _ = jnp.linalg.qr(y)
    for _ in range(niter):
        z, _ = jnp.linalg.qr(cmm_t(qmat))
        qmat, _ = jnp.linalg.qr(cmm(z))
    b = cmm_t(qmat).T                       # (p, M)
    ub, s_, vt = jnp.linalg.svd(b, full_matrices=False)
    return (Tensor((qmat @ ub)[:, :q]), Tensor(s_[:q]),
            Tensor(vt.T[:, :q]))
