"""``paddle.sparse.nn`` (ref: ``python/paddle/sparse/nn/``): activations,
batch norm over sparse values, and submanifold-free conv fallback."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..tensor import Tensor

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "functional"]


def _map_values(x, fn):
    from . import SparseCooTensor, SparseCsrTensor
    if isinstance(x, SparseCsrTensor):
        b = x._bcsr
        return SparseCsrTensor(jsparse.BCSR(
            (fn(b.data), b.indices, b.indptr), shape=b.shape))
    b = x._bcoo
    return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices),
                                        shape=b.shape))


class _ValueActivation:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x):
        return _map_values(x, self._fn)


class ReLU(_ValueActivation):
    def __init__(self):
        super().__init__(jax.nn.relu)


class ReLU6(_ValueActivation):
    def __init__(self):
        super().__init__(lambda v: jnp.clip(v, 0, 6))


class LeakyReLU(_ValueActivation):
    def __init__(self, negative_slope=0.01):
        super().__init__(lambda v: jnp.where(v >= 0, v,
                                             negative_slope * v))


class Softmax:
    """CSR row-softmax over stored values (ref sparse softmax semantics)."""

    def __init__(self, axis=-1):
        self.axis = axis

    def __call__(self, x):
        from . import SparseCsrTensor
        if not isinstance(x, SparseCsrTensor):
            raise TypeError("sparse softmax expects a CSR tensor")
        b = x._bcsr
        dense = b.todense()
        mask = dense != 0
        neg = jnp.where(mask, dense, -jnp.inf)
        sm = jax.nn.softmax(neg, axis=self.axis)
        sm = jnp.where(mask, sm, 0)
        coo = jsparse.BCOO.fromdense(sm, nse=b.nse)
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(coo))


class BatchNorm:
    """BatchNorm over sparse values per channel (last dim of values)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = Tensor(jnp.ones(num_features))
        self.bias = Tensor(jnp.zeros(num_features))

    def __call__(self, x):
        def f(v):
            m = v.mean(axis=0, keepdims=True)
            var = v.var(axis=0, keepdims=True)
            out = (v - m) * jax.lax.rsqrt(var + self.epsilon)
            return out * self.weight._data + self.bias._data
        return _map_values(x, f)


class functional:
    relu = staticmethod(lambda x: ReLU()(x))
    relu6 = staticmethod(lambda x: ReLU6()(x))
    leaky_relu = staticmethod(
        lambda x, negative_slope=0.01: LeakyReLU(negative_slope)(x))
    softmax = staticmethod(lambda x, axis=-1: Softmax(axis)(x))
