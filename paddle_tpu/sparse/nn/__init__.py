"""``paddle.sparse.nn`` layers (ref: ``python/paddle/sparse/nn/``;
conv layers ``layer/conv.py:239 Conv3D`` / ``:509 SubmConv3D``).

See ``functional`` for the TPU realization (scatter → dense XLA op on
the MXU → gather at the rulebook output pattern, tape-recorded).
"""
from __future__ import annotations

import numpy as np

from ...nn.layer.layers import Layer
from ...nn import initializer as I
from . import functional  # noqa: F401
from . import functional as F

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
           "MaxPool3D"]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class _SparseConv(Layer):
    def __init__(self, nd, subm, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None, key=None):
        super().__init__()
        if padding_mode != "zeros":
            raise NotImplementedError("sparse conv padding_mode")
        self._nd = nd
        self._subm = subm
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        k = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size,) * nd
        # paddle sparse weight layout: DHWIO (spatial..., in, out)
        fan_in = int(np.prod(k)) * in_channels
        self.weight = self.create_parameter(
            list(k) + [in_channels, out_channels], attr=weight_attr,
            default_initializer=I.Normal(std=(2.0 / fan_in) ** 0.5))
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        fn = {(2, False): F.conv2d, (2, True): F.subm_conv2d,
              (3, False): F.conv3d, (3, True): F.subm_conv3d}[
            (self._nd, self._subm)]
        return fn(x, self.weight, bias=self.bias, stride=self.stride,
                  padding=self.padding, dilation=self.dilation,
                  groups=self.groups)


class Conv3D(_SparseConv):
    """ref ``sparse/nn/layer/conv.py:239``."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(3, False, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)


class SubmConv3D(_SparseConv):
    """ref ``sparse/nn/layer/conv.py:509``: output sites == input sites."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(3, True, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, key=key)


class Conv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(2, False, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)


class SubmConv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(2, True, in_channels, out_channels, kernel_size,
                         stride, padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, key=key)


class BatchNorm(Layer):
    """Sparse batch norm over active values (ref ``sparse/nn/layer/
    norm.py BatchNorm``)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self.momentum = momentum
        self.epsilon = epsilon
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        from ...tensor import Tensor
        import jax.numpy as jnp
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, weight=self.weight,
            bias=self.bias, training=self.training,
            momentum=self.momentum, epsilon=self.epsilon,
            use_global_stats=self.use_global_stats)


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse BN. On TPU the stats ride the same GSPMD
    machinery as dense SyncBatchNorm — under a data-parallel mesh the
    value statistics are computed over the global (sharded) nnz axis by
    XLA; single-process semantics equal BatchNorm (ref
    ``sparse/nn/layer/norm.py SyncBatchNorm``)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(
                layer, SyncBatchNorm):
            new = cls.__new__(cls)
            new.__dict__.update(layer.__dict__)
            return new
        for name, sub in list(getattr(layer, "_sub_layers", {}).items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        if return_mask:
            raise NotImplementedError(
                "sparse MaxPool3D return_mask is not supported")
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, stride=self.stride,
                            padding=self.padding, ceil_mode=self.ceil_mode)
