"""``paddle.sparse.nn.functional`` (ref: ``python/paddle/sparse/nn/
functional/``; kernels ``paddle/phi/kernels/sparse/gpu/conv_kernel.cu``).

TPU design: XLA has no sparse-conv primitive, and on the MXU a dense
conv over the scattered activations is the fast realization at the
densities these layers see in practice — so each op is
scatter(values) → dense XLA op → gather(output pattern), all recorded on
the tape (grads flow to values AND layer parameters). The output
sparsity pattern is computed from the INPUT pattern alone (the
reference's rulebook semantics, not value thresholding):

 - subm_conv*: output pattern == input pattern (submanifold rule)
 - conv* / max_pool3d: a site is active iff its kernel window touches an
   active input site — a host-side numpy union over kernel offsets
   (the reference builds the same product set on device, conv_kernel.cu
   ProductRuleBook).
"""
from __future__ import annotations

import itertools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ....tensor import Tensor
from ....ops.op_utils import ensure_tensor, nary
from ... import SparseCooTensor, SparseCsrTensor
from ...import _coo

__all__ = ["conv2d", "conv3d", "subm_conv2d", "subm_conv3d", "max_pool3d",
           "relu", "relu6", "leaky_relu", "softmax", "attention",
           "batch_norm"]


def _tup(v, n):
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v),) * n


def _values_tensor(x: SparseCooTensor) -> Tensor:
    if getattr(x, "_values_t", None) is not None:
        return x._values_t
    return Tensor(x._bcoo.data)


def _host_indices(x: SparseCooTensor) -> np.ndarray:
    return np.asarray(x._bcoo.indices)  # [nnz, 1 + nd] (batch + spatial)


def _out_pattern(idx, spatial_in, kernel, stride, padding, dilation,
                 ceil_mode=False):
    """Active output sites for a standard sparse conv/pool: union over
    kernel offsets of reachable strided positions. Host-side numpy —
    pattern discovery is data-dependent (dynamic nnz), exactly like the
    reference's rulebook build."""
    nd = len(kernel)

    def _osz(si, k, s, p, d):
        num = si + 2 * p - (d * (k - 1) + 1)
        return (num + s - 1) // s + 1 if ceil_mode else num // s + 1

    spatial_out = tuple(
        _osz(si, k, s, p, d)
        for si, k, s, p, d in zip(spatial_in, kernel, stride, padding,
                                  dilation))
    batch = idx[:, 0]
    sp = idx[:, 1:1 + nd]
    outs = []
    for off in itertools.product(*(range(k) for k in kernel)):
        cand = sp + np.asarray(padding) - np.asarray(off) * np.asarray(
            dilation)
        ok = np.ones(len(cand), bool)
        for a in range(nd):
            ok &= (cand[:, a] % stride[a] == 0)
        pos = cand // np.asarray(stride)
        for a in range(nd):
            ok &= (pos[:, a] >= 0) & (pos[:, a] < spatial_out[a])
        if ok.any():
            outs.append(np.concatenate(
                [batch[ok, None], pos[ok]], axis=1))
    if not outs:
        return np.zeros((0, 1 + nd), np.int32), spatial_out
    uni = np.unique(np.concatenate(outs, axis=0), axis=0)
    return uni.astype(np.int32), spatial_out


def _conv(x, weight, bias, stride, padding, dilation, groups, subm, nd,
          opname):
    """Shared sparse conv: NDHWC/NHWC input, DHWIO/HWIO weight (paddle
    sparse layout)."""
    if groups != 1:
        raise NotImplementedError("sparse conv groups > 1")
    kernel_t = ensure_tensor(weight)
    k = tuple(int(s) for s in kernel_t.shape[:nd])
    stride = _tup(stride, nd)
    padding = _tup(padding, nd)
    dilation = _tup(dilation, nd)
    shape = tuple(x.shape)
    spatial_in = shape[1:1 + nd]
    cin, cout = int(kernel_t.shape[nd]), int(kernel_t.shape[nd + 1])
    idx = _host_indices(x)
    if subm:
        if stride != (1,) * nd:
            raise ValueError("subm conv requires stride 1")
        out_idx, spatial_out = idx.astype(np.int32), spatial_in
    else:
        out_idx, spatial_out = _out_pattern(idx, spatial_in, k, stride,
                                            padding, dilation)
    out_shape = (shape[0],) + tuple(spatial_out) + (cout,)
    vals_t = _values_tensor(x)
    args = [vals_t, kernel_t]
    if bias is not None:
        args.append(ensure_tensor(bias))
    idx_j = jnp.asarray(idx)
    out_idx_j = jnp.asarray(out_idx)
    dn = lax.conv_dimension_numbers(
        (1,) * (nd + 2), (1,) * (nd + 2),
        ("NDHWC" if nd == 3 else "NHWC",
         "DHWIO" if nd == 3 else "HWIO",
         "NDHWC" if nd == 3 else "NHWC"))

    def f(vals, w, *b):
        dense = jnp.zeros(shape[:1 + nd] + (cin,), vals.dtype)
        dense = dense.at[tuple(idx_j[:, i] for i in range(1 + nd))].set(vals)
        out = lax.conv_general_dilated(
            dense, w, window_strides=stride,
            padding=[(p, p) for p in padding], rhs_dilation=dilation,
            dimension_numbers=dn)
        if b:
            out = out + b[0]
        return out[tuple(out_idx_j[:, i] for i in range(1 + nd))]

    out_vals = nary(f, args, name=opname)
    from ....sparse import sparse_coo_tensor
    return sparse_coo_tensor(Tensor(out_idx_j.T), out_vals,
                             shape=out_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """ref ``sparse/nn/functional/conv.py conv3d``."""
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=False, nd=3, opname="sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv (ref ``conv.py subm_conv3d``): output pattern ==
    input pattern."""
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=True, nd=3, opname="sparse_subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=False, nd=2, opname="sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 subm=True, nd=2, opname="sparse_subm_conv2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse max pool (ref ``sparse/nn/functional/pooling.py``): dense
    reduce_window over the scattered sites, output pattern from the
    input pattern."""
    nd = 3
    k = _tup(kernel_size, nd)
    stride = _tup(stride if stride is not None else kernel_size, nd)
    padding = _tup(padding, nd)
    shape = tuple(x.shape)
    spatial_in = shape[1:1 + nd]
    C = shape[-1]
    idx = _host_indices(x)
    out_idx, spatial_out = _out_pattern(idx, spatial_in, k, stride, padding,
                                        (1,) * nd, ceil_mode=ceil_mode)
    out_shape = (shape[0],) + tuple(spatial_out) + (C,)
    idx_j = jnp.asarray(idx)
    out_idx_j = jnp.asarray(out_idx)
    vals_t = _values_tensor(x)

    def f(vals):
        dense = jnp.full(shape[:1 + nd] + (C,), -jnp.inf, vals.dtype)
        dense = dense.at[tuple(idx_j[:, i] for i in range(1 + nd))].set(vals)
        pads = [(0, 0)] + [
            (p, p + (s - 1 if ceil_mode else 0))
            for p, s in zip(padding, stride)] + [(0, 0)]
        out = lax.reduce_window(
            dense, -jnp.inf, lax.max, (1,) + k + (1,), (1,) + stride + (1,),
            pads)
        return out[tuple(out_idx_j[:, i] for i in range(1 + nd))]

    out_vals = nary(f, [vals_t], name="sparse_max_pool3d")
    from ....sparse import sparse_coo_tensor
    return sparse_coo_tensor(Tensor(out_idx_j.T), out_vals, shape=out_shape)


def _value_unary(fn, opname):
    def op(x, *fargs, name=None):
        vals_t = _values_tensor(x)
        out_vals = nary(lambda v: fn(v, *fargs), [vals_t], name=opname)
        b = x._bcoo
        import jax.experimental.sparse as jsparse
        return SparseCooTensor(
            jsparse.BCOO((out_vals._data, b.indices), shape=b.shape),
            values_t=out_vals)
    return op


relu = _value_unary(lambda v: jnp.maximum(v, 0), "sparse_relu")
relu6 = _value_unary(lambda v: jnp.clip(v, 0, 6), "sparse_relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _value_unary(
        lambda v: jnp.where(v > 0, v, v * negative_slope),
        "sparse_leaky_relu")(x)


def _row_softmax(vals_t, row_ids, nrows, opname):
    def f(vals):
        v32 = vals.astype(jnp.float32)
        vmax = jax.ops.segment_max(v32, row_ids, num_segments=nrows)
        shifted = jnp.exp(v32 - vmax[row_ids])
        denom = jax.ops.segment_sum(shifted, row_ids, num_segments=nrows)
        return (shifted / denom[row_ids]).astype(vals.dtype)
    return nary(f, [vals_t], name=opname)


def softmax(x, axis=-1, name=None):
    """Per-row softmax over the stored values (ref
    ``sparse/nn/functional/activation.py softmax``, axis=-1 only).

    COO input keeps its value order AND tape link (gradients flow from
    downstream ops to upstream sparse layers); CSR input records from
    its stored values."""
    if axis != -1:
        raise ValueError("sparse softmax only supports axis=-1")
    import jax.experimental.sparse as jsparse
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        if b.data.ndim > 1:
            # trailing DENSE dims (e.g. channels): axis=-1 is a plain
            # per-site softmax over the dense axis
            out_vals = nary(
                lambda v: jax.nn.softmax(
                    v.astype(jnp.float32), axis=-1).astype(v.dtype),
                [_values_tensor(x)], name="sparse_softmax")
            return SparseCooTensor(
                jsparse.BCOO((out_vals._data, b.indices), shape=b.shape),
                values_t=out_vals)
        idx = np.asarray(b.indices)
        if idx.shape[0] != len(np.unique(idx, axis=0)):
            raise ValueError("sparse softmax requires a coalesced COO "
                             "tensor (call coalesce() first)")
        # fully sparse: rows = flattened leading sparse dims
        lead_shape = tuple(x.shape[:idx.shape[1] - 1])
        row_ids = jnp.asarray(np.ravel_multi_index(
            tuple(idx[:, a] for a in range(idx.shape[1] - 1)),
            lead_shape).astype(np.int32))
        nrows = int(np.prod(lead_shape))
        out_vals = _row_softmax(_values_tensor(x), row_ids, nrows,
                                "sparse_softmax")
        return SparseCooTensor(
            jsparse.BCOO((out_vals._data, b.indices), shape=b.shape),
            values_t=out_vals)
    if isinstance(x, SparseCsrTensor):
        b = x._bcsr
        indptr = np.asarray(b.indptr)
        if indptr.ndim > 1:
            raise NotImplementedError("batched CSR softmax")
        nrows = indptr.shape[0] - 1
        row_ids = jnp.asarray(np.repeat(np.arange(nrows),
                                        np.diff(indptr)).astype(np.int32))
        out_vals = _row_softmax(Tensor(b.data), row_ids, nrows,
                                "sparse_softmax")
        return SparseCsrTensor(jsparse.BCSR(
            (out_vals._data, b.indices, b.indptr), shape=b.shape))
    raise TypeError("sparse softmax expects a sparse tensor")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NDHWC", use_global_stats=None, name=None):
    """Channel batch-norm over the ACTIVE values only (ref
    ``sparse/nn/layer/norm.py BatchNorm``: stats over nnz, not the
    zero-filled dense volume)."""
    vals_t = _values_tensor(x)
    rm = ensure_tensor(running_mean)
    rv = ensure_tensor(running_var)
    use_stats = (not training) if use_global_stats is None \
        else use_global_stats

    args = [vals_t]
    for t in (weight, bias):
        if t is not None:
            args.append(ensure_tensor(t))
    has_w = weight is not None
    has_b = bias is not None

    def f(vals, *wb):
        v32 = vals.astype(jnp.float32)
        if use_stats:
            mean, var = rm._data, rv._data
        else:
            mean = jnp.mean(v32, axis=0)
            var = jnp.var(v32, axis=0)
        out = (v32 - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out.astype(vals.dtype)

    out_vals = nary(f, args, name="sparse_batch_norm")
    if training and not use_stats:
        # running-stat update (host path, like the dense BN layer)
        v32 = np.asarray(vals_t._data, np.float32) \
            if not isinstance(vals_t._data, jax.core.Tracer) else None
        if v32 is not None:
            m, v = v32.mean(0), v32.var(0)
            rm._data = rm._data * momentum + m * (1 - momentum)
            rv._data = rv._data * momentum + v * (1 - momentum)
    b = x._bcoo
    import jax.experimental.sparse as jsparse
    return SparseCooTensor(
        jsparse.BCOO((out_vals._data, b.indices), shape=b.shape),
        values_t=out_vals)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse transformer attention (ref ``sparse/nn/functional/
    transformer.py attention``): q/k/v dense [B, H, S, D]; sparse_mask a
    CSR [B*H, S, S] pattern. Rides the dense-masked
    ``F.sparse_attention`` realization."""
    from ....nn.functional.common import sparse_attention as _dense_sa
    q = ensure_tensor(query)
    B, H, S, _ = q.shape
    b = sparse_mask._bcsr
    indptr = jnp.asarray(b.indptr).reshape(B, H, S + 1)
    cols = jnp.asarray(b.indices).reshape(B, H, -1)
    return _dense_sa(q, key, value, Tensor(indptr), Tensor(cols),
                     key_padding_mask=key_padding_mask,
                     attn_mask=attn_mask)
