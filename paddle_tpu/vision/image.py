"""``paddle.vision.image`` (ref: ``python/paddle/vision/image.py``):
global image-loading backend switch + ``image_load`` used by
DatasetFolder/ImageFolder."""
from __future__ import annotations

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend):
    """'pil' or 'cv2' (both available in this environment)."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2'], but got "
            f"{backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Returns PIL.Image ('pil') or HWC BGR np.ndarray ('cv2'), exactly
    as the reference's loaders do."""
    if backend is None:
        backend = _image_backend
    if backend == "pil":
        from PIL import Image
        return Image.open(path)
    if backend == "cv2":
        import cv2
        img = cv2.imread(path)  # IMREAD_COLOR: 3-channel BGR (ref)
        if img is None:
            # cv2 signals missing/corrupt/unsupported files with None,
            # which would fail far downstream inside a transform
            raise ValueError(f"cv2 could not read image: {path!r}")
        return img
    raise ValueError(
        f"Expected backend are one of ['pil', 'cv2'], but got {backend}")
