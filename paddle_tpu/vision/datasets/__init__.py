"""Vision datasets (ref: ``python/paddle/vision/datasets/``).

Zero-egress environment: datasets load from local files (`data_file=`) in
the reference's formats; `FakeData` provides deterministic synthetic data
for benchmarks and tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io.dataset import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST", "FashionMNIST", "FakeData",
           "DatasetFolder", "ImageFolder"]

from .folder import DatasetFolder, ImageFolder  # noqa: E402,F401


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, size=1000, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        # per-class fixed signal so models can actually learn
        self._centers = self._rng.randn(num_classes,
                                        *self.image_shape).astype(np.float32)

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        label = idx % self.num_classes
        img = (self._centers[label]
               + 0.5 * rng.randn(*self.image_shape).astype(np.float32))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return self.size


class Cifar10(Dataset):
    """CIFAR-10 from the standard python-version tar.gz (ref:
    ``vision/datasets/cifar.py``). Pass data_file=path/to/
    cifar-10-python.tar.gz."""

    MODE_TRAIN_BATCHES = [f"data_batch_{i}" for i in range(1, 6)]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar10 requires data_file=<path to cifar-10-python.tar.gz> "
                "(no network download in this environment); use "
                "paddle_tpu.vision.datasets.FakeData for synthetic data")
        self.transform = transform
        self.mode = mode
        datas, labels = [], []
        wanted = self.MODE_TRAIN_BATCHES if mode == "train" else ["test_batch"]
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in wanted:
                    d = pickle.loads(tf.extractfile(member).read(),
                                     encoding="bytes")
                    datas.append(d[b"data"])
                    labels.extend(d[b"labels"])
        self.data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC uint8
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32) / 255.0
            img = img.transpose(2, 0, 1)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    MODE_TRAIN_BATCHES = ["train"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar100 requires data_file=<path to "
                "cifar-100-python.tar.gz>")
        self.transform = transform
        self.mode = mode
        wanted = "train" if mode == "train" else "test"
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if os.path.basename(member.name) == wanted:
                    d = pickle.loads(tf.extractfile(member).read(),
                                     encoding="bytes")
                    self.data = d[b"data"].reshape(-1, 3, 32, 32)
                    self.labels = np.asarray(d[b"fine_labels"],
                                             dtype=np.int64)
                    break


class MNIST(Dataset):
    """MNIST from the idx-format gz files (ref: vision/datasets/mnist.py)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        if image_path is None or label_path is None or \
                not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"{type(self).__name__} requires image_path/label_path to "
                "local idx .gz files (no network download); use FakeData "
                "for synthetic data")
        self.transform = transform
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8).astype(
                np.int64)
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
