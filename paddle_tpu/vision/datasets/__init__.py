"""Vision datasets (ref: ``python/paddle/vision/datasets/``).

Zero-egress environment: datasets load from local files (`data_file=`) in
the reference's formats; `FakeData` provides deterministic synthetic data
for benchmarks and tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io.dataset import Dataset


from ...utils.download import _safe_extractall  # noqa: E402  (shared
# fail-closed tar extraction — one policy for every extraction site)

__all__ = ["Cifar10", "Cifar100", "MNIST", "FashionMNIST", "FakeData",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]

from .folder import DatasetFolder, ImageFolder  # noqa: E402,F401


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, size=1000, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        # per-class fixed signal so models can actually learn
        self._centers = self._rng.randn(num_classes,
                                        *self.image_shape).astype(np.float32)

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        label = idx % self.num_classes
        img = (self._centers[label]
               + 0.5 * rng.randn(*self.image_shape).astype(np.float32))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return self.size


class Cifar10(Dataset):
    """CIFAR-10 from the standard python-version tar.gz (ref:
    ``vision/datasets/cifar.py``). Pass data_file=path/to/
    cifar-10-python.tar.gz."""

    MODE_TRAIN_BATCHES = [f"data_batch_{i}" for i in range(1, 6)]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar10 requires data_file=<path to cifar-10-python.tar.gz> "
                "(no network download in this environment); use "
                "paddle_tpu.vision.datasets.FakeData for synthetic data")
        self.transform = transform
        self.mode = mode
        datas, labels = [], []
        wanted = self.MODE_TRAIN_BATCHES if mode == "train" else ["test_batch"]
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in wanted:
                    d = pickle.loads(tf.extractfile(member).read(),
                                     encoding="bytes")
                    datas.append(d[b"data"])
                    labels.extend(d[b"labels"])
        self.data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC uint8
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32) / 255.0
            img = img.transpose(2, 0, 1)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    MODE_TRAIN_BATCHES = ["train"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar100 requires data_file=<path to "
                "cifar-100-python.tar.gz>")
        self.transform = transform
        self.mode = mode
        wanted = "train" if mode == "train" else "test"
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if os.path.basename(member.name) == wanted:
                    d = pickle.loads(tf.extractfile(member).read(),
                                     encoding="bytes")
                    self.data = d[b"data"].reshape(-1, 3, 32, 32)
                    self.labels = np.asarray(d[b"fine_labels"],
                                             dtype=np.int64)
                    break


class MNIST(Dataset):
    """MNIST from the idx-format gz files (ref: vision/datasets/mnist.py)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        if image_path is None or label_path is None or \
                not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"{type(self).__name__} requires image_path/label_path to "
                "local idx .gz files (no network download); use FakeData "
                "for synthetic data")
        self.transform = transform
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8).astype(
                np.int64)
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Flowers(Dataset):
    """Flowers-102 (ref ``vision/datasets/flowers.py``): (image, label).

    Pass data_file=<102flowers.tgz> + label_file=<imagelabels.mat> +
    setid_file=<setid.mat> (the reference's three downloads; decoded
    with PIL + scipy like the reference's backends), or
    ``synthetic=True`` for per-class generated images (no network in
    this environment). Reference semantics preserved: the train/test
    split arrays are deliberately EXCHANGED (train uses ``tstid``, the
    larger set), labels are the raw 1-based values with shape (1,), and
    item order follows setid.mat file order.
    """

    # the reference swaps these on purpose (flowers.py MODE_FLAG_MAP)
    _SPLIT_KEY = {"train": "tstid", "valid": "valid", "test": "trnid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, backend="cv2",
                 synthetic=False, n_samples=128):
        mode = str(mode).lower()
        if mode not in self._SPLIT_KEY:
            raise ValueError(f"mode must be train/valid/test, got {mode!r}")
        if backend not in ("pil", "cv2"):
            raise ValueError(f"backend must be 'pil' or 'cv2', got "
                             f"{backend!r}")
        self.transform = transform
        self.backend = backend
        self._fake = None
        if synthetic:
            self._fake = FakeData(size=n_samples, image_shape=(3, 64, 64),
                                  num_classes=102,
                                  seed=0 if mode == "train" else 1)
            return
        for f in (data_file, label_file, setid_file):
            if f is None or not os.path.exists(f):
                raise FileNotFoundError(
                    "Flowers requires data_file/label_file/setid_file "
                    "(no network download); or pass synthetic=True")
        import scipy.io as sio
        labels = sio.loadmat(label_file)["labels"][0]       # 1-based
        # file order preserved: sample i matches the reference's sample i
        self._ids = [int(i) for i in
                     sio.loadmat(setid_file)[self._SPLIT_KEY[mode]][0]]
        self._labels = {i: int(labels[i - 1]) for i in self._ids}
        # extract ONCE (the tgz is gzip — members are not seekable, so
        # per-item extractfile would re-decompress the archive each time;
        # the reference extracts to disk in __init__ too)
        import tempfile
        self._tmpdir = tempfile.TemporaryDirectory(prefix="flowers_")
        self._dir = self._tmpdir.name  # reclaimed with the dataset object
        with tarfile.open(data_file) as tf:
            _safe_extractall(tf, self._dir)
        self._paths = {}
        for root, _, files in os.walk(self._dir):
            for name in files:
                if name.endswith(".jpg"):
                    self._paths[name] = os.path.join(root, name)

    def __getitem__(self, idx):
        if self._fake is not None:
            img, label = self._fake[idx]
            if self.transform is not None:
                img = self.transform(img)
            return img, label
        from ..image import image_load
        img_id = self._ids[idx]
        # same contract as image_load: 'pil' -> PIL.Image, 'cv2' -> BGR
        img = image_load(self._paths[f"image_{img_id:05d}.jpg"],
                         backend=self.backend)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self._labels[img_id]], np.int64)

    def __len__(self):
        if self._fake is not None:
            return len(self._fake)
        return len(self._ids)


class VOC2012(Dataset):
    """VOC2012 segmentation (ref ``vision/datasets/voc2012.py``):
    (image, mask) pairs; synthetic mode generates blob masks so
    segmentation pipelines are testable offline."""

    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend="cv2", synthetic=False, n_samples=64,
                 image_shape=(3, 64, 64)):
        self.transform = transform
        self.image_shape = tuple(image_shape)
        if not synthetic:
            if data_file is not None and os.path.exists(data_file):
                raise NotImplementedError(
                    "jpeg/png decoding needs an image library; use "
                    "synthetic=True or a DatasetFolder of decoded arrays")
            raise FileNotFoundError(
                "VOC2012 requires the VOCtrainval tar (no network "
                "download in this environment); pass synthetic=True for "
                "generated (image, mask) pairs")
        self.n = n_samples
        self.seed = 0 if mode == "train" else 1

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed * 100003 + idx)
        c, h, w = self.image_shape
        img = rng.rand(c, h, w).astype(np.float32)
        mask = np.zeros((h, w), np.int64)
        # a couple of rectangular "objects"
        for _ in range(rng.randint(1, 4)):
            cls = rng.randint(1, self.NUM_CLASSES)
            y0, x0 = rng.randint(0, h // 2), rng.randint(0, w // 2)
            mask[y0:y0 + rng.randint(4, h // 2),
                 x0:x0 + rng.randint(4, w // 2)] = cls
            img[:, mask == cls] += cls / self.NUM_CLASSES
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return self.n
