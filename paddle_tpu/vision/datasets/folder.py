"""Folder-based datasets (ref: ``python/paddle/vision/datasets/folder.py``
DatasetFolder / ImageFolder): class-per-subdirectory image trees, no
download required — the natural air-gapped dataset format."""
from __future__ import annotations

import os

import numpy as np

from ...io.dataset import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "default_loader"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def default_loader(path):
    """Dispatches on the global image backend
    (``paddle.vision.set_image_backend``), like the reference; .npy
    arrays load directly."""
    if path.endswith(".npy"):
        return np.load(path)
    from ..image import get_image_backend, image_load
    if get_image_backend() == "cv2":
        return image_load(path, backend="cv2")
    from PIL import Image
    with open(path, "rb") as f:
        return np.asarray(Image.open(f).convert("RGB"))


def _is_valid(path, extensions):
    return path.lower().endswith(tuple(extensions))


class DatasetFolder(Dataset):
    """root/class_x/xxx.ext layout → (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or default_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = is_valid_file(path) if is_valid_file else \
                        _is_valid(path, extensions)
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise FileNotFoundError(
                f"no files with extensions {extensions} under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (unlabeled) image folder → [sample] (ref ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or default_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    _is_valid(path, extensions)
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise FileNotFoundError(
                f"no files with extensions {extensions} under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
