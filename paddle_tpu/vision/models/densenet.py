"""DenseNet (ref: ``python/paddle/vision/models/densenet.py``)."""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]


class _DenseLayer(Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(num_channels)
        self.conv1 = nn.Conv2D(num_channels, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.relu = nn.ReLU()

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        from ...ops.manipulation import concat
        return concat([x, out], axis=1)


class _DenseBlock(Layer):
    def __init__(self, num_layers, num_channels, growth_rate, bn_size,
                 dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(num_channels + i * growth_rate, growth_rate,
                        bn_size, dropout)
            for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(Layer):
    def __init__(self, num_channels, num_output):
        super().__init__()
        self.bn = nn.BatchNorm2D(num_channels)
        self.conv = nn.Conv2D(num_channels, num_output, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class DenseNet(Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        num_init, growth_rate, block_cfg = _CFG[layers]
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        ch = num_init
        for i, n in enumerate(block_cfg):
            blocks.append(_DenseBlock(n, ch, growth_rate, bn_size, dropout))
            ch += n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch //= 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_last = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_last(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _make(layers, **kw):
    return DenseNet(layers=layers, **kw)


def densenet121(pretrained=False, **kw):
    return _make(121, **kw)


def densenet161(pretrained=False, **kw):
    return _make(161, **kw)


def densenet169(pretrained=False, **kw):
    return _make(169, **kw)


def densenet201(pretrained=False, **kw):
    return _make(201, **kw)


def densenet264(pretrained=False, **kw):
    return _make(264, **kw)
