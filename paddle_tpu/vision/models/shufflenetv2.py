"""ShuffleNetV2 (ref: ``python/paddle/vision/models/shufflenetv2.py``)."""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ... import nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def _channel_shuffle(x, groups):
    from ...ops.manipulation import reshape, transpose
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class _InvertedResidual(Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_ch = out_ch // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), _act(act),
                nn.Conv2D(branch_ch, branch_ch, 3, stride=1, padding=1,
                          groups=branch_ch, bias_attr=False),
                nn.BatchNorm2D(branch_ch),
                nn.Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), _act(act))
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), _act(act))
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), _act(act),
                nn.Conv2D(branch_ch, branch_ch, 3, stride=stride, padding=1,
                          groups=branch_ch, bias_attr=False),
                nn.BatchNorm2D(branch_ch),
                nn.Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), _act(act))

    def forward(self, x):
        from ...ops.manipulation import concat, split
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        out_ch = _STAGE_OUT[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, out_ch[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(out_ch[0]), _act(act))
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        in_ch = out_ch[0]
        for stage, repeats in enumerate(stage_repeats):
            oc = out_ch[stage + 1]
            for i in range(repeats):
                blocks.append(_InvertedResidual(in_ch, oc,
                                                2 if i == 0 else 1, act))
                in_ch = oc
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, out_ch[-1], 1, bias_attr=False),
            nn.BatchNorm2D(out_ch[-1]), _act(act))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(out_ch[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.blocks(self.max_pool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.0, act="swish", **kw)
