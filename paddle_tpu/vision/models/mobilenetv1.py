"""MobileNetV1 (ref: ``python/paddle/vision/models/mobilenetv1.py``)."""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class _ConvBNRelu(Layer):
    def __init__(self, in_ch, out_ch, kernel, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, kernel, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _DepthwiseSeparable(Layer):
    def __init__(self, in_ch, out1, out2, stride, scale):
        super().__init__()
        c1, c2, c3 = int(in_ch * scale), int(out1 * scale), int(out2 * scale)
        self.dw = _ConvBNRelu(c1, c2, 3, stride=stride, padding=1, groups=c1)
        self.pw = _ConvBNRelu(c2, c3, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = scale
        self.conv1 = _ConvBNRelu(3, int(32 * s), 3, stride=2, padding=1)
        cfg = [  # in, out1, out2, stride (per reference)
            (32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
            (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
            (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
            (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
            (1024, 1024, 1024, 1)]
        self.blocks = nn.Sequential(*[
            _DepthwiseSeparable(i, o1, o2, st, s) for (i, o1, o2, st) in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * s), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)
