"""GoogLeNet / Inception v1 (ref: ``python/paddle/vision/models/
googlenet.py``)."""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ... import nn

__all__ = ["GoogLeNet", "googlenet"]


class _Inception(Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        R = nn.ReLU
        self.b1 = nn.Sequential(nn.Conv2D(in_ch, c1, 1), R())
        self.b2 = nn.Sequential(nn.Conv2D(in_ch, c3r, 1), R(),
                                nn.Conv2D(c3r, c3, 3, padding=1), R())
        self.b3 = nn.Sequential(nn.Conv2D(in_ch, c5r, 1), R(),
                                nn.Conv2D(c5r, c5, 5, padding=2), R())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(in_ch, proj, 1), R())

    def forward(self, x):
        from ...ops.manipulation import concat
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(Layer):
    """Returns (out, aux1, aux2) like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        R = nn.ReLU
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), R(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), R(),
            nn.Conv2D(64, 192, 3, padding=1), R(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.ince3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.ince4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.ince5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (train-time deep supervision, ref googlenet.py)
            self.aux1 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Conv2D(512, 128, 1), R())
            self.aux1_fc = nn.Sequential(nn.Linear(2048, 1024), R(),
                                         nn.Dropout(0.7),
                                         nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), nn.Conv2D(528, 128, 1), R())
            self.aux2_fc = nn.Sequential(nn.Linear(2048, 1024), R(),
                                         nn.Dropout(0.7),
                                         nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.ince3b(self.ince3a(x)))
        a = self.ince4a(x)
        b = self.ince4d(self.ince4c(self.ince4b(a)))
        x = self.pool4(self.ince4e(b))
        x = self.ince5b(self.ince5a(x))
        out = aux1 = aux2 = None
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            out = self.fc(self.dropout(x.flatten(1)))
            aux1 = self.aux1_fc(self.aux1(a).flatten(1))
            aux2 = self.aux2_fc(self.aux2(b).flatten(1))
            return out, aux1, aux2
        return x


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)
