"""Functional image transforms (ref: ``python/paddle/vision/transforms/
functional.py``; geometric kernels ``functional_pil.py`` /
``functional_cv2.py``).

Numpy/HWC implementations: one inverse-mapping warp engine drives affine /
rotate / perspective (the reference delegates to PIL's ``Image.transform``
with the same inverse matrices). Host-side by design — augmentation runs in
dataloader workers on CPU, keeping the TPU step graph static-shaped.
"""
from __future__ import annotations

import math
import numbers

import numpy as np

from ...tensor import Tensor

__all__ = ["pad", "affine", "rotate", "perspective", "to_grayscale",
           "adjust_brightness", "adjust_contrast", "adjust_saturation",
           "adjust_hue", "erase"]


def _as_hwc(img):
    unwrap = isinstance(img, Tensor)
    arr = np.asarray(img._data) if unwrap else np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _restore(out, img):
    if isinstance(img, Tensor):
        return Tensor(out)
    return out


def _clip_like(out, ref_dtype):
    if ref_dtype == np.uint8:
        return np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out.astype(np.float32)


# -- pad --------------------------------------------------------------------
_PAD_MODES = {"constant": "constant", "edge": "edge", "reflect": "reflect",
              "symmetric": "symmetric"}


def pad(img, padding, fill=0, padding_mode="constant"):
    """Pad on all sides (ref ``functional.py pad``): padding is int,
    (left/right, top/bottom) or (left, top, right, bottom)."""
    arr = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        p = [int(padding)] * 4
    else:
        p = [int(v) for v in padding]
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
    if padding_mode not in _PAD_MODES:
        raise ValueError(f"padding_mode must be one of {list(_PAD_MODES)}")
    widths = [(p[1], p[3]), (p[0], p[2]), (0, 0)]
    if padding_mode == "constant":
        if isinstance(fill, (tuple, list)):
            if len(fill) != arr.shape[2]:
                raise ValueError(
                    f"pad fill has {len(fill)} values but the image has "
                    f"{arr.shape[2]} channels")
            # per-channel fill: pad each channel plane separately
            out = np.stack([
                np.pad(arr[..., ci], widths[:2], constant_values=fv)
                for ci, fv in enumerate(fill)], axis=2)
        else:
            out = np.pad(arr, widths, constant_values=fill)
    else:
        out = np.pad(arr, widths, mode=_PAD_MODES[padding_mode])
    return _restore(out, img)


# -- warp engine ------------------------------------------------------------
def _warp(arr, inv3x3, out_hw, interpolation="nearest", fill=0):
    """Inverse-mapping resample: for each output pixel, apply ``inv3x3`` to
    (x, y, 1) to find the source location; sample nearest/bilinear; pixels
    mapping outside the input get ``fill``."""
    H, W = arr.shape[:2]
    oh, ow = out_hw
    ys, xs = np.meshgrid(np.arange(oh, dtype=np.float64),
                         np.arange(ow, dtype=np.float64), indexing="ij")
    denom = inv3x3[2, 0] * xs + inv3x3[2, 1] * ys + inv3x3[2, 2]
    denom = np.where(np.abs(denom) < 1e-12, 1e-12, denom)
    xin = (inv3x3[0, 0] * xs + inv3x3[0, 1] * ys + inv3x3[0, 2]) / denom
    yin = (inv3x3[1, 0] * xs + inv3x3[1, 1] * ys + inv3x3[1, 2]) / denom

    f = arr.astype(np.float32)
    if np.isscalar(fill):
        fillv = np.full((arr.shape[2],), float(fill), np.float32)
    else:
        fillv = np.asarray(fill, np.float32)
    if interpolation in ("nearest", 0):
        xi = np.round(xin).astype(np.int64)
        yi = np.round(yin).astype(np.int64)
        valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
        out = f[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)]
        out = np.where(valid[..., None], out, fillv)
    else:  # bilinear
        x0 = np.floor(xin).astype(np.int64)
        y0 = np.floor(yin).astype(np.int64)
        x1, y1 = x0 + 1, y0 + 1
        wx = (xin - x0)[..., None].astype(np.float32)
        wy = (yin - y0)[..., None].astype(np.float32)

        def sample(yy, xx):
            v = f[np.clip(yy, 0, H - 1), np.clip(xx, 0, W - 1)]
            ok = (xx >= 0) & (xx < W) & (yy >= 0) & (yy < H)
            return np.where(ok[..., None], v, fillv)

        out = (sample(y0, x0) * (1 - wy) * (1 - wx) +
               sample(y0, x1) * (1 - wy) * wx +
               sample(y1, x0) * wy * (1 - wx) +
               sample(y1, x1) * wy * wx)
    return _clip_like(out, arr.dtype)


def _inverse_affine_matrix(center, angle, translate, scale, shear):
    """Inverse (output->input) affine matrix, the standard PIL/torchvision
    parameterization: rotate about ``center`` by ``angle`` degrees CCW,
    shear (x, y) degrees, scale, then translate."""
    rot = math.radians(angle)
    sx = math.radians(shear[0])
    sy = math.radians(shear[1])
    cx, cy = center
    tx, ty = translate

    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)

    m = [d / scale, -b / scale, 0.0, -c / scale, a / scale, 0.0]
    m[2] += m[0] * (-cx - tx) + m[1] * (-cy - ty)
    m[5] += m[3] * (-cx - tx) + m[4] * (-cy - ty)
    m[2] += cx
    m[5] += cy
    return np.array([[m[0], m[1], m[2]], [m[3], m[4], m[5]],
                     [0.0, 0.0, 1.0]], np.float64)


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0, 0),
           interpolation="nearest", fill=0, center=None):
    """Affine transform (ref ``functional.py affine``)."""
    arr = _as_hwc(img)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    if scale <= 0:
        raise ValueError("scale must be positive")
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    inv = _inverse_affine_matrix(center, angle, translate, scale,
                                 tuple(shear))
    return _restore(_warp(arr, inv, (H, W), interpolation, fill), img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate ``angle`` degrees counter-clockwise (ref ``functional.py
    rotate``); ``expand`` grows the canvas to hold the whole rotated
    image (only valid for rotation about the image center).

    Note the convention split the reference also has: ``rotate`` is CCW
    (PIL semantics) while ``affine``'s angle is clockwise."""
    arr = _as_hwc(img)
    angle = -angle  # the shared matrix is clockwise-positive
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    if not expand:
        inv = _inverse_affine_matrix(center, angle, (0, 0), 1.0, (0, 0))
        return _restore(_warp(arr, inv, (H, W), interpolation, fill), img)
    # expanded canvas: the rotated corners' bbox sets the output size
    # (symmetric in the angle's sign, so the cw/ccw flip doesn't matter)
    rot = math.radians(angle)
    cosr, sinr = math.cos(rot), math.sin(rot)
    cx, cy = (W - 1) * 0.5, (H - 1) * 0.5
    corners = np.array([[0, 0], [W - 1, 0], [W - 1, H - 1], [0, H - 1]],
                       np.float64) - [cx, cy]
    rc = corners @ np.array([[cosr, sinr], [-sinr, cosr]]).T
    ow = int(math.ceil(rc[:, 0].max() - rc[:, 0].min() + 1))
    oh = int(math.ceil(rc[:, 1].max() - rc[:, 1].min() + 1))
    # same clockwise matrix as the non-expand path; the translate term
    # re-centers expanded-output coords onto the input canvas first
    ocx, ocy = (ow - 1) * 0.5, (oh - 1) * 0.5
    inv = _inverse_affine_matrix((cx, cy), angle, (ocx - cx, ocy - cy),
                                 1.0, (0, 0))
    return _restore(_warp(arr, inv, (oh, ow), interpolation, fill), img)


def _perspective_coeffs(startpoints, endpoints):
    """Solve the 8-dof homography (output->input), torchvision/PIL
    parameterization: maps each endpoint to its startpoint."""
    a = np.zeros((8, 8), np.float64)
    b = np.zeros((8,), np.float64)
    for i, ((sx, sy), (ex, ey)) in enumerate(zip(startpoints, endpoints)):
        a[2 * i] = [ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey]
        a[2 * i + 1] = [0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey]
        b[2 * i] = sx
        b[2 * i + 1] = sy
    h = np.linalg.solve(a, b)
    return np.array([[h[0], h[1], h[2]], [h[3], h[4], h[5]],
                     [h[6], h[7], 1.0]], np.float64)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective transform mapping ``startpoints`` (in the input) to
    ``endpoints`` (in the output) — ref ``functional.py perspective``."""
    arr = _as_hwc(img)
    H, W = arr.shape[:2]
    inv = _perspective_coeffs(startpoints, endpoints)
    return _restore(_warp(arr, inv, (H, W), interpolation, fill), img)


# -- photometric ------------------------------------------------------------
def to_grayscale(img, num_output_channels=1):
    """ITU-R 601-2 luma (what PIL's ``convert('L')`` uses)."""
    arr = _as_hwc(img)
    f = arr.astype(np.float32)
    gray = (f[..., :3] @ np.array([0.299, 0.587, 0.114],
                                  np.float32))[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=2)
    elif num_output_channels != 1:
        raise ValueError("num_output_channels should be either 1 or 3")
    return _restore(_clip_like(gray, arr.dtype), img)


def adjust_brightness(img, brightness_factor):
    """``img * factor`` (PIL ImageEnhance.Brightness semantics)."""
    if brightness_factor < 0:
        raise ValueError("brightness_factor is not non-negative.")
    arr = _as_hwc(img)
    out = arr.astype(np.float32) * brightness_factor
    return _restore(_clip_like(out, arr.dtype), img)


def adjust_contrast(img, contrast_factor):
    """Blend with the mean gray level (PIL ImageEnhance.Contrast)."""
    if contrast_factor < 0:
        raise ValueError("contrast_factor is not non-negative.")
    arr = _as_hwc(img)
    f = arr.astype(np.float32)
    gray = f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)
    mean = np.round(gray.mean()) if arr.dtype == np.uint8 else gray.mean()
    out = f * contrast_factor + mean * (1 - contrast_factor)
    return _restore(_clip_like(out, arr.dtype), img)


def adjust_saturation(img, saturation_factor):
    """Blend with the grayscale image (PIL ImageEnhance.Color)."""
    if saturation_factor < 0:
        raise ValueError("saturation_factor is not non-negative.")
    arr = _as_hwc(img)
    f = arr.astype(np.float32)
    gray = (f[..., :3] @ np.array([0.299, 0.587, 0.114],
                                  np.float32))[..., None]
    out = f * saturation_factor + gray * (1 - saturation_factor)
    return _restore(_clip_like(out, arr.dtype), img)


def _rgb_to_hsv(rgb):
    """Vectorized RGB->HSV on [0,1] floats (colorsys convention)."""
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, axis=-1)
    minc = np.min(rgb, axis=-1)
    v = maxc
    rng = maxc - minc
    s = np.where(maxc > 0, rng / np.where(maxc > 0, maxc, 1), 0.0)
    safe = np.where(rng > 0, rng, 1.0)
    rc = (maxc - r) / safe
    gc = (maxc - g) / safe
    bc = (maxc - b) / safe
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(rng > 0, (h / 6.0) % 1.0, 0.0)
    return h, s, v


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int64) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([r, g, b], axis=-1)


def adjust_hue(img, hue_factor):
    """Shift hue by ``hue_factor`` of a full HSV turn, in [-0.5, 0.5]
    (ref ``functional.py adjust_hue``)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor is not in [-0.5, 0.5].")
    arr = _as_hwc(img)
    if arr.shape[2] < 3:
        # grayscale has no hue (PIL 'L'-mode behavior: unchanged)
        return img
    scale = 255.0 if arr.dtype == np.uint8 else 1.0
    f = arr.astype(np.float32)[..., :3] / scale
    h, s, v = _rgb_to_hsv(f)
    h = (h + hue_factor) % 1.0
    out = _hsv_to_rgb(h, s, v) * scale
    if arr.shape[2] > 3:
        out = np.concatenate([out, arr[..., 3:].astype(np.float32)], -1)
    return _restore(_clip_like(out, arr.dtype), img)


def erase(img, i, j, h, w, v, inplace=False):
    """Fill the (i, j, h, w) region with ``v`` (ref ``functional.py
    erase``). Works on HWC numpy and on CHW Tensors (paddle's RandomErasing
    runs after ToTensor)."""
    if isinstance(img, Tensor):
        # np.asarray over a jax array is a read-only view — always copy
        out = np.array(img._data)
        val = np.asarray(v, out.dtype) if not np.isscalar(v) else v
        if out.ndim == 3:  # CHW
            out[..., i:i + h, j:j + w] = val
        else:
            out[i:i + h, j:j + w] = val
        if inplace:
            import jax.numpy as jnp
            img._data = jnp.asarray(out)
            return img
        return Tensor(out)
    arr = np.asarray(img)
    out = arr if inplace else arr.copy()
    out[i:i + h, j:j + w] = np.asarray(v, out.dtype) if not np.isscalar(v) \
        else v
    return out
