"""Vision transforms (ref: ``python/paddle/vision/transforms/``).

Numpy/HWC-based (no PIL dependency); ToTensor converts to CHW float.
"""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from ...tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "RandomResizedCrop", "Transpose", "Pad", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "RandomRotation", "Grayscale", "BaseTransform",
           "to_tensor", "normalize", "resize", "hflip", "vflip", "crop",
           "center_crop"]


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(img, data_format="CHW"):
    img = _as_hwc(img)
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    if isinstance(size, int):
        h, w = img.shape[:2]
        if h < w:
            new_h, new_w = size, int(size * w / h)
        else:
            new_h, new_w = int(size * h / w), size
    else:
        new_h, new_w = size
    # simple numpy bilinear/nearest resize
    h, w = img.shape[:2]
    if (h, w) == (new_h, new_w):
        return img
    ys = np.linspace(0, h - 1, new_h)
    xs = np.linspace(0, w - 1, new_w)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None],
                  np.round(xs).astype(int)[None, :]]
    else:
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        f = img.astype(np.float32)
        out = (f[y0[:, None], x0[None, :]] * (1 - wy) * (1 - wx) +
               f[y1[:, None], x0[None, :]] * wy * (1 - wx) +
               f[y0[:, None], x1[None, :]] * (1 - wy) * wx +
               f[y1[:, None], x1[None, :]] * wy * wx)
        if img.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = img.shape[:2]
    th, tw = output_size
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return crop(img, i, j, th, tw)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else \
                [self.padding] * 4
            img = np.pad(img, [(p[1], p[3]), (p[0], p[2]), (0, 0)])
        h, w = img.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return img
        i = pyrandom.randint(0, h - th)
        j = pyrandom.randint(0, w - tw)
        return crop(img, i, j, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import math
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = pyrandom.uniform(*self.scale) * area
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target_area * ar)))
            ch = int(round(math.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                i = pyrandom.randint(0, h - ch)
                j = pyrandom.randint(0, w - cw)
                return resize(crop(img, i, j, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        self.padding = p
        self.fill = fill

    def _apply_image(self, img):
        img = _as_hwc(img)
        p = self.padding
        return np.pad(img, [(p[1], p[3]), (p[0], p[2]), (0, 0)],
                      constant_values=self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img)
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        out = img.astype(np.float32) * alpha
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img)
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        mean = img.astype(np.float32).mean()
        out = img.astype(np.float32) * alpha + mean * (1 - alpha)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img)
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        gray = img.astype(np.float32).mean(axis=2, keepdims=True)
        out = img.astype(np.float32) * alpha + gray * (1 - alpha)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        # cheap approximation: channel roll-mix
        img = _as_hwc(img)
        f = pyrandom.uniform(-self.value, self.value)
        out = img.astype(np.float32)
        rolled = np.roll(out, 1, axis=2)
        out = out * (1 - abs(f)) + rolled * abs(f)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, numbers.Number) else degrees

    def _apply_image(self, img):
        # right-angle rotations only (exact, no scipy dependency)
        img = _as_hwc(img)
        angle = pyrandom.uniform(*self.degrees)
        k = int(round(angle / 90.0)) % 4
        return np.rot90(img, k=k, axes=(0, 1)).copy()


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _as_hwc(img).astype(np.float32)
        gray = (img[..., :3] @ np.array([0.299, 0.587, 0.114],
                                        np.float32))[..., None]
        if self.num_output_channels == 3:
            gray = np.repeat(gray, 3, axis=2)
        return gray
