"""Vision transforms (ref: ``python/paddle/vision/transforms/``).

Numpy/HWC-based (no PIL dependency); ToTensor converts to CHW float.
"""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from ...tensor import Tensor

from . import functional  # noqa: F401
from .functional import (  # noqa: F401
    pad, affine, rotate, perspective, to_grayscale, adjust_brightness,
    adjust_contrast, adjust_saturation, adjust_hue, erase)

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "RandomResizedCrop", "Transpose", "Pad", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "RandomRotation", "Grayscale", "BaseTransform",
           "RandomAffine", "RandomPerspective", "RandomErasing",
           "to_tensor", "normalize", "resize", "hflip", "vflip", "crop",
           "center_crop", "pad", "affine", "rotate", "perspective",
           "to_grayscale", "adjust_brightness", "adjust_contrast",
           "adjust_saturation", "adjust_hue", "erase"]


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(img, data_format="CHW"):
    img = _as_hwc(img)
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    if isinstance(size, int):
        h, w = img.shape[:2]
        if h < w:
            new_h, new_w = size, int(size * w / h)
        else:
            new_h, new_w = int(size * h / w), size
    else:
        new_h, new_w = size
    # simple numpy bilinear/nearest resize
    h, w = img.shape[:2]
    if (h, w) == (new_h, new_w):
        return img
    ys = np.linspace(0, h - 1, new_h)
    xs = np.linspace(0, w - 1, new_w)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None],
                  np.round(xs).astype(int)[None, :]]
    else:
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        f = img.astype(np.float32)
        out = (f[y0[:, None], x0[None, :]] * (1 - wy) * (1 - wx) +
               f[y1[:, None], x0[None, :]] * wy * (1 - wx) +
               f[y0[:, None], x1[None, :]] * (1 - wy) * wx +
               f[y1[:, None], x1[None, :]] * wy * wx)
        if img.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = img.shape[:2]
    th, tw = output_size
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return crop(img, i, j, th, tw)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else \
                [self.padding] * 4
            img = np.pad(img, [(p[1], p[3]), (p[0], p[2]), (0, 0)])
        h, w = img.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return img
        i = pyrandom.randint(0, h - th)
        j = pyrandom.randint(0, w - tw)
        return crop(img, i, j, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import math
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = pyrandom.uniform(*self.scale) * area
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target_area * ar)))
            ch = int(round(math.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                i = pyrandom.randint(0, h - ch)
                j = pyrandom.randint(0, w - cw)
                return resize(crop(img, i, j, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, fill=self.fill,
                   padding_mode=self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img)
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        out = img.astype(np.float32) * alpha
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img)
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        mean = img.astype(np.float32).mean()
        out = img.astype(np.float32) * alpha + mean * (1 - alpha)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img)
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        gray = img.astype(np.float32).mean(axis=2, keepdims=True)
        out = img.astype(np.float32) * alpha + gray * (1 - alpha)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        return adjust_hue(img, pyrandom.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, numbers.Number) else tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = pyrandom.uniform(*self.degrees)
        return rotate(img, angle, interpolation=self.interpolation,
                      expand=self.expand, center=self.center,
                      fill=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _as_hwc(img).astype(np.float32)
        gray = (img[..., :3] @ np.array([0.299, 0.587, 0.114],
                                        np.float32))[..., None]
        if self.num_output_channels == 3:
            gray = np.repeat(gray, 3, axis=2)
        return gray


class RandomAffine(BaseTransform):
    """Random affine: rotation/translate/scale/shear sampled per call
    (ref ``transforms.py:1385 RandomAffine``)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, numbers.Number) else tuple(degrees)
        if translate is not None:
            for t in translate:
                if not 0.0 <= t <= 1.0:
                    raise ValueError(
                        "translation values should be between 0 and 1")
        self.translate = translate
        if scale is not None and any(s <= 0 for s in scale):
            raise ValueError("scale values should be positive")
        self.scale = scale
        if isinstance(shear, numbers.Number):
            shear = (-shear, shear)
        self.shear = tuple(shear) if shear is not None else None
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _get_param(self, img_size):
        w, h = img_size
        angle = pyrandom.uniform(*self.degrees)
        if self.translate is not None:
            max_dx = self.translate[0] * w
            max_dy = self.translate[1] * h
            tx = int(round(pyrandom.uniform(-max_dx, max_dx)))
            ty = int(round(pyrandom.uniform(-max_dy, max_dy)))
        else:
            tx = ty = 0
        scale = pyrandom.uniform(*self.scale) if self.scale else 1.0
        if self.shear is not None:
            sx = pyrandom.uniform(self.shear[0], self.shear[1])
            sy = pyrandom.uniform(*self.shear[2:4]) \
                if len(self.shear) == 4 else 0.0
        else:
            sx = sy = 0.0
        return angle, (tx, ty), scale, (sx, sy)

    def _apply_image(self, img):
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        angle, translate, scale, shear = self._get_param((w, h))
        return affine(img, angle, translate=translate, scale=scale,
                      shear=shear, interpolation=self.interpolation,
                      fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    """Random four-corner perspective distortion
    (ref ``transforms.py:1836 RandomPerspective``)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        if not 0 <= prob <= 1:
            raise ValueError("prob must be in [0, 1]")
        if not 0 <= distortion_scale <= 1:
            raise ValueError("distortion_scale must be in [0, 1]")
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _get_param(self, width, height):
        d = self.distortion_scale
        half_w, half_h = width // 2, height // 2
        tl = (pyrandom.randint(0, int(d * half_w)),
              pyrandom.randint(0, int(d * half_h)))
        tr = (width - 1 - pyrandom.randint(0, int(d * half_w)),
              pyrandom.randint(0, int(d * half_h)))
        br = (width - 1 - pyrandom.randint(0, int(d * half_w)),
              height - 1 - pyrandom.randint(0, int(d * half_h)))
        bl = (pyrandom.randint(0, int(d * half_w)),
              height - 1 - pyrandom.randint(0, int(d * half_h)))
        start = [(0, 0), (width - 1, 0), (width - 1, height - 1),
                 (0, height - 1)]
        return start, [tl, tr, br, bl]

    def _apply_image(self, img):
        if pyrandom.random() >= self.prob:
            return img
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        start, end = self._get_param(w, h)
        return perspective(img, start, end,
                           interpolation=self.interpolation,
                           fill=self.fill)


class RandomErasing(BaseTransform):
    """Random rectangle erasure, the Zhong et al. augmentation
    (ref ``transforms.py RandomErasing``); runs after ToTensor in the
    reference recipes, so CHW Tensors and HWC arrays both work."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        if not (isinstance(scale, (tuple, list)) and len(scale) == 2):
            raise TypeError("scale should be a tuple or list of length 2")
        if not 0 <= scale[0] <= scale[1] <= 1:
            raise ValueError("scale should be of kind (min, max) in [0,1]")
        if ratio[0] > ratio[1]:
            raise ValueError("ratio should be of kind (min, max)")
        if not isinstance(value, (numbers.Number, str, tuple, list)):
            raise TypeError("value must be a number, 'random', or sequence")
        if isinstance(value, str) and value != "random":
            raise ValueError("value must be 'random' when str")
        self.prob = prob
        self.scale = tuple(scale)
        self.ratio = tuple(ratio)
        self.value = value
        self.inplace = inplace

    def _get_param(self, h, w, c):
        import math
        area = h * w
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target / ar)))
            ew = int(round(math.sqrt(target * ar)))
            if eh < h and ew < w and eh > 0 and ew > 0:
                i = pyrandom.randint(0, h - eh)
                j = pyrandom.randint(0, w - ew)
                if self.value == "random":
                    v = np.random.normal(size=(eh, ew, c)).astype(np.float32)
                elif isinstance(self.value, (tuple, list)):
                    v = np.asarray(self.value, np.float32).reshape(1, 1, c)
                    v = np.broadcast_to(v, (eh, ew, c))
                else:
                    v = self.value
                return i, j, eh, ew, v
        return None

    def _apply_image(self, img):
        if pyrandom.random() >= self.prob:
            return img
        chw_tensor = isinstance(img, Tensor) and img.ndim == 3
        if chw_tensor:
            c, h, w = img.shape
        else:
            arr = _as_hwc(img)
            h, w, c = arr.shape
            img = arr
        param = self._get_param(h, w, c)
        if param is None:
            return img
        i, j, eh, ew, v = param
        if chw_tensor and not np.isscalar(v):
            v = np.transpose(v, (2, 0, 1))  # CHW region fill
        return erase(img, i, j, eh, ew, v, inplace=self.inplace)
