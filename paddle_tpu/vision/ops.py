"""Vision ops (ref: ``python/paddle/vision/ops.py``): boxes, RoI, deform
conv subset. Box utilities are pure jnp; RoIAlign uses gather-based bilinear
sampling (one XLA gather instead of a custom CUDA kernel)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops.op_utils import ensure_tensor, nary, unary as _unary

__all__ = ["box_coder", "box_area", "box_iou", "nms", "roi_align",
           "roi_pool", "generate_proposals", "distribute_fpn_proposals",
           "yolo_box", "yolo_loss", "DeformConv2D", "deform_conv2d",
           "PSRoIPool", "psroi_pool", "RoIAlign", "RoIPool"]


def box_area(boxes, name=None):
    return _unary(lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                  boxes, name="box_area")


def box_iou(boxes1, boxes2, name=None):
    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return nary(f, [ensure_tensor(boxes1), ensure_tensor(boxes2)],
                name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (data-dependent output size — eager only, like the
    reference's CPU fallback path)."""
    b = np.asarray(ensure_tensor(boxes)._data, dtype=np.float64)
    s = np.asarray(ensure_tensor(scores)._data) if scores is not None \
        else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(ensure_tensor(boxes_num)._data)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat, rois):
        n_rois = rois.shape[0]
        C = feat.shape[1]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        # one sample per bin center (sampling_ratio=1 equivalent)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5) / oh * rh[:, None]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5) / ow * rw[:, None]

        outs = []
        for r in range(n_rois):
            fmap = feat[batch_idx[r]]  # (C, H, W)
            yy, xx = ys[r], xs[r]
            H, W = fmap.shape[-2:]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy - y0, 0, 1)[:, None]
            wx = jnp.clip(xx - x0, 0, 1)[None, :]
            v00 = fmap[:, y0][:, :, x0]
            v01 = fmap[:, y0][:, :, x1_]
            v10 = fmap[:, y1_][:, :, x0]
            v11 = fmap[:, y1_][:, :, x1_]
            out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                   v10 * wy * (1 - wx) + v11 * wy * wx)
            outs.append(out)
        return jnp.stack(outs) if outs else jnp.zeros((0, C, oh, ow),
                                                      feat.dtype)
    return nary(f, [x, boxes], name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    # max-pool variant of roi_align with nearest binning
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(ensure_tensor(boxes_num)._data)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat, rois):
        outs = []
        H, W = feat.shape[-2:]
        for r in range(rois.shape[0]):
            fmap = feat[batch_idx[r]]
            x1 = jnp.round(rois[r, 0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(rois[r, 1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.maximum(jnp.round(rois[r, 2] * spatial_scale), x1 + 1)
            y2 = jnp.maximum(jnp.round(rois[r, 3] * spatial_scale), y1 + 1)
            ys = jnp.clip(jnp.linspace(y1, y2, oh + 1), 0, H).astype(jnp.int32)
            xs = jnp.clip(jnp.linspace(x1, x2, ow + 1), 0, W).astype(jnp.int32)
            # fixed-size gather grid (8 samples per bin edge-to-edge)
            gy = jnp.clip((ys[:-1, None] + jnp.arange(8)[None, :]), 0, H - 1)
            gx = jnp.clip((xs[:-1, None] + jnp.arange(8)[None, :]), 0, W - 1)
            patch = fmap[:, gy][:, :, :, gx]  # C, oh, 8, ow, 8
            outs.append(patch.max(axis=(2, 4)))
        return jnp.stack(outs)
    return nary(f, [x, boxes], name="roi_pool")


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def f(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            ox = (tx - px) / pw / pbv[:, 0]
            oy = (ty - py) / ph / pbv[:, 1]
            ow = jnp.log(tw / pw) / pbv[:, 2]
            oh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([ox, oy, ow, oh], axis=1)
        ox = pbv[:, 0] * tb[..., 0] * pw + px
        oy = pbv[:, 1] * tb[..., 1] * ph + py
        ow = jnp.exp(pbv[:, 2] * tb[..., 2]) * pw
        oh = jnp.exp(pbv[:, 3] * tb[..., 3]) * ph
        return jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2,
                          oy + oh / 2], axis=-1)
    return nary(f, [ensure_tensor(prior_box), ensure_tensor(prior_box_var),
                    ensure_tensor(target_box)], name="box_coder")


def generate_proposals(*args, **kwargs):
    raise NotImplementedError(
        "generate_proposals: detection-specific dynamic-shape op; planned "
        "via fixed-size top-k + masking")


def distribute_fpn_proposals(*args, **kwargs):
    raise NotImplementedError("distribute_fpn_proposals: planned")


def yolo_box(*args, **kwargs):
    raise NotImplementedError("yolo_box: planned")


def yolo_loss(*args, **kwargs):
    raise NotImplementedError("yolo_loss: planned")


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d: planned as gather-based sampling + matmul")


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError("DeformConv2D: planned")


def psroi_pool(*args, **kwargs):
    raise NotImplementedError("psroi_pool: planned")


class PSRoIPool:
    def __init__(self, *a, **k):
        raise NotImplementedError("PSRoIPool: planned")
