"""Vision ops (ref: ``python/paddle/vision/ops.py``): boxes, RoI, deform
conv subset. Box utilities are pure jnp; RoIAlign uses gather-based bilinear
sampling (one XLA gather instead of a custom CUDA kernel)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops.op_utils import ensure_tensor, nary, unary as _unary

__all__ = ["box_coder", "box_area", "box_iou", "nms", "roi_align",
           "roi_pool", "generate_proposals", "distribute_fpn_proposals",
           "yolo_box", "yolo_loss", "DeformConv2D", "deform_conv2d",
           "PSRoIPool", "psroi_pool", "RoIAlign", "RoIPool"]


def box_area(boxes, name=None):
    return _unary(lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                  boxes, name="box_area")


def box_iou(boxes1, boxes2, name=None):
    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return nary(f, [ensure_tensor(boxes1), ensure_tensor(boxes2)],
                name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (data-dependent output size — eager only, like the
    reference's CPU fallback path)."""
    b = np.asarray(ensure_tensor(boxes)._data, dtype=np.float64)
    s = np.asarray(ensure_tensor(scores)._data) if scores is not None \
        else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(ensure_tensor(boxes_num)._data)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat, rois):
        n_rois = rois.shape[0]
        C = feat.shape[1]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        # one sample per bin center (sampling_ratio=1 equivalent)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5) / oh * rh[:, None]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5) / ow * rw[:, None]

        outs = []
        for r in range(n_rois):
            fmap = feat[batch_idx[r]]  # (C, H, W)
            yy, xx = ys[r], xs[r]
            H, W = fmap.shape[-2:]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy - y0, 0, 1)[:, None]
            wx = jnp.clip(xx - x0, 0, 1)[None, :]
            v00 = fmap[:, y0][:, :, x0]
            v01 = fmap[:, y0][:, :, x1_]
            v10 = fmap[:, y1_][:, :, x0]
            v11 = fmap[:, y1_][:, :, x1_]
            out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                   v10 * wy * (1 - wx) + v11 * wy * wx)
            outs.append(out)
        return jnp.stack(outs) if outs else jnp.zeros((0, C, oh, ow),
                                                      feat.dtype)
    return nary(f, [x, boxes], name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    # max-pool variant of roi_align with nearest binning
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(ensure_tensor(boxes_num)._data)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat, rois):
        outs = []
        H, W = feat.shape[-2:]
        for r in range(rois.shape[0]):
            fmap = feat[batch_idx[r]]
            x1 = jnp.round(rois[r, 0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(rois[r, 1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.maximum(jnp.round(rois[r, 2] * spatial_scale), x1 + 1)
            y2 = jnp.maximum(jnp.round(rois[r, 3] * spatial_scale), y1 + 1)
            ys = jnp.clip(jnp.linspace(y1, y2, oh + 1), 0, H).astype(jnp.int32)
            xs = jnp.clip(jnp.linspace(x1, x2, ow + 1), 0, W).astype(jnp.int32)
            # fixed-size gather grid (8 samples per bin edge-to-edge)
            gy = jnp.clip((ys[:-1, None] + jnp.arange(8)[None, :]), 0, H - 1)
            gx = jnp.clip((xs[:-1, None] + jnp.arange(8)[None, :]), 0, W - 1)
            patch = fmap[:, gy][:, :, :, gx]  # C, oh, 8, ow, 8
            outs.append(patch.max(axis=(2, 4)))
        return jnp.stack(outs)
    return nary(f, [x, boxes], name="roi_pool")


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def f(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            ox = (tx - px) / pw / pbv[:, 0]
            oy = (ty - py) / ph / pbv[:, 1]
            ow = jnp.log(tw / pw) / pbv[:, 2]
            oh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([ox, oy, ow, oh], axis=1)
        ox = pbv[:, 0] * tb[..., 0] * pw + px
        oy = pbv[:, 1] * tb[..., 1] * ph + py
        ow = jnp.exp(pbv[:, 2] * tb[..., 2]) * pw
        oh = jnp.exp(pbv[:, 3] * tb[..., 3]) * ph
        return jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2,
                          oy + oh / 2], axis=-1)
    return nary(f, [ensure_tensor(prior_box), ensure_tensor(prior_box_var),
                    ensure_tensor(target_box)], name="box_coder")


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (ref ``python/paddle/vision/ops.py``
    generate_proposals → ``phi/kernels/gpu/generate_proposals_kernel.cu``).

    Host-side like :func:`nms` (data-dependent output sizes — the
    reference also emits LoD rois): per image, decode anchor deltas,
    clip to the image, drop boxes under ``min_size``, keep the
    ``pre_nms_top_n`` best, NMS, keep ``post_nms_top_n``.

    scores ``[N, A, H, W]``; bbox_deltas ``[N, 4A, H, W]``; img_size
    ``[N, 2]`` (h, w); anchors/variances ``[H, W, A, 4]``. Returns
    (rois ``[R, 4]``, roi_probs ``[R, 1]``[, rois_num ``[N]``]).
    """
    sc = np.asarray(ensure_tensor(scores)._data, np.float32)
    de = np.asarray(ensure_tensor(bbox_deltas)._data, np.float32)
    iszs = np.asarray(ensure_tensor(img_size)._data, np.float32)
    an = np.asarray(ensure_tensor(anchors)._data, np.float32).reshape(-1, 4)
    va = np.asarray(ensure_tensor(variances)._data,
                    np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    offset = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        # [A,H,W] -> [H,W,A] -> flat, matching the anchors' [H,W,A,4]
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = de[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = min(pre_nms_top_n, s.size) if pre_nms_top_n > 0 else s.size
        order = np.argsort(-s)[:k]
        s, d, a, v = s[order], d[order], an[order], va[order]
        # decode (variance-scaled center-size transform)
        aw = a[:, 2] - a[:, 0] + offset
        ah = a[:, 3] - a[:, 1] + offset
        acx = a[:, 0] + 0.5 * aw
        acy = a[:, 1] + 0.5 * ah
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        wN = np.exp(np.minimum(v[:, 2] * d[:, 2], np.log(1000. / 16.))) * aw
        hN = np.exp(np.minimum(v[:, 3] * d[:, 3], np.log(1000. / 16.))) * ah
        boxes = np.stack([cx - 0.5 * wN, cy - 0.5 * hN,
                          cx + 0.5 * wN - offset,
                          cy + 0.5 * hN - offset], axis=1)
        imh, imw = iszs[n, 0], iszs[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, imw - offset)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, imh - offset)
        ws = boxes[:, 2] - boxes[:, 0] + offset
        hs = boxes[:, 3] - boxes[:, 1] + offset
        keep = (ws >= min_size) & (hs >= min_size)
        boxes, s = boxes[keep], s[keep]
        if boxes.shape[0]:
            keep_idx = np.asarray(
                nms(Tensor(jnp.asarray(boxes)), iou_threshold=nms_thresh,
                    scores=Tensor(jnp.asarray(s)),
                    top_k=post_nms_top_n)._data)
            boxes, s = boxes[keep_idx], s[keep_idx]
        all_rois.append(boxes)
        all_probs.append(s[:, None])
        nums.append(len(boxes))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)
                              if all_rois else np.zeros((0, 4), np.float32)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)
                               if all_probs else np.zeros((0, 1),
                                                          np.float32)))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels by scale (ref ``python/paddle/vision/
    ops.py distribute_fpn_proposals``): level = floor(log2(sqrt(area) /
    refer_scale) + refer_level), clipped to [min_level, max_level].
    Returns (multi_rois per level, restore_ind[, rois_num_per_level])."""
    r = np.asarray(ensure_tensor(fpn_rois)._data, np.float32)
    offset = 1.0 if pixel_offset else 0.0
    w = r[:, 2] - r[:, 0] + offset
    h = r[:, 3] - r[:, 1] + offset
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8) + refer_level)
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)

    # image id of every roi (from the per-image counts, when batched)
    if rois_num is not None:
        counts = np.asarray(ensure_tensor(rois_num)._data,
                            np.int64).ravel()
        img_of = np.repeat(np.arange(counts.size), counts)
    else:
        counts = np.array([r.shape[0]], np.int64)
        img_of = np.zeros(r.shape[0], np.int64)

    multi_rois, lvl_nums, order = [], [], []
    for l in range(min_level, max_level + 1):
        idx = np.where(lvl == l)[0]
        order.append(idx)
        multi_rois.append(Tensor(jnp.asarray(
            r[idx] if idx.size else np.zeros((0, 4), np.float32))))
        # per-IMAGE counts at this level, shape [N] (ref semantics)
        lvl_nums.append(np.bincount(img_of[idx],
                                    minlength=counts.size).astype(np.int32))
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    restore_ind = Tensor(jnp.asarray(restore[:, None].astype(np.int32)))
    if rois_num is not None:
        return multi_rois, restore_ind, [Tensor(jnp.asarray(n))
                                         for n in lvl_nums]
    return multi_rois, restore_ind


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 head (ref ``python/paddle/vision/ops.py yolo_box``
    → ``phi/kernels/.../yolo_box_kernel``): pure jnp, jit-friendly.

    x ``[N, an*(5+class_num), H, W]``; img_size ``[N, 2]`` (h, w).
    Returns (boxes ``[N, an*H*W, 4]`` xyxy, scores ``[N, an*H*W,
    class_num]``); predictions under ``conf_thresh`` are zeroed like the
    reference.
    """
    def f(feat, im):
        an = jnp.asarray(np.asarray(anchors, np.float32).reshape(-1, 2))
        n_anchor = an.shape[0]
        N, C, H, W = feat.shape
        iou_pred = None
        if iou_aware:
            # PP-YOLO layout: [N, an + an*(5+cls), H, W] — the per-anchor
            # IoU logits come first (ref yolo_box kernel entry_index)
            iou_pred = jax.nn.sigmoid(feat[:, :n_anchor])  # [N, an, H, W]
            feat = feat[:, n_anchor:]
        p = feat.reshape(N, n_anchor, 5 + class_num, H, W)
        p = jnp.moveaxis(p, 2, -1)            # [N, an, H, W, 5+cls]
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        alpha = scale_x_y
        beta = -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(p[..., 0]) * alpha + beta + gx) / W
        cy = (jax.nn.sigmoid(p[..., 1]) * alpha + beta + gy) / H
        input_w = jnp.float32(downsample_ratio * W)
        input_h = jnp.float32(downsample_ratio * H)
        bw = jnp.exp(p[..., 2]) * an[None, :, None, None, 0] / input_w
        bh = jnp.exp(p[..., 3]) * an[None, :, None, None, 1] / input_h
        conf = jax.nn.sigmoid(p[..., 4])
        if iou_pred is not None:
            # IoU-aware rescoring: conf^(1-f) * iou^f
            conf = conf ** (1.0 - iou_aware_factor) * \
                iou_pred ** iou_aware_factor
        cls = jax.nn.sigmoid(p[..., 5:]) * conf[..., None]
        imh = im[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = im[:, 1].astype(jnp.float32)[:, None, None, None]
        x0 = (cx - bw / 2) * imw
        y0 = (cy - bh / 2) * imh
        x1 = (cx + bw / 2) * imw
        y1 = (cy + bh / 2) * imh
        if clip_bbox:
            x0 = jnp.clip(x0, 0, imw - 1)
            y0 = jnp.clip(y0, 0, imh - 1)
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1)
        mask = (conf >= conf_thresh).astype(boxes.dtype)
        boxes = boxes * mask[..., None]
        cls = cls * mask[..., None]
        return (boxes.reshape(N, -1, 4),
                cls.reshape(N, -1, class_num))

    return nary(f, [ensure_tensor(x), ensure_tensor(img_size)],
                name="yolo_box", n_out=2)


def yolo_loss(*args, **kwargs):
    raise NotImplementedError("yolo_loss: planned")


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d: planned as gather-based sampling + matmul")


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError("DeformConv2D: planned")


def psroi_pool(*args, **kwargs):
    raise NotImplementedError("psroi_pool: planned")


class PSRoIPool:
    def __init__(self, *a, **k):
        raise NotImplementedError("PSRoIPool: planned")
