"""Vision ops (ref: ``python/paddle/vision/ops.py``): boxes, RoI, deform
conv subset. Box utilities are pure jnp; RoIAlign uses gather-based bilinear
sampling (one XLA gather instead of a custom CUDA kernel)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops.op_utils import ensure_tensor, nary, unary as _unary
from ..nn.layer.layers import Layer

__all__ = ["box_coder", "box_area", "box_iou", "nms", "roi_align",
           "roi_pool", "generate_proposals", "distribute_fpn_proposals",
           "yolo_box", "yolo_loss", "DeformConv2D", "deform_conv2d",
           "PSRoIPool", "psroi_pool", "RoIAlign", "RoIPool",
           "read_file", "decode_jpeg", "prior_box", "matrix_nms",
           "ConvNormActivation"]


def box_area(boxes, name=None):
    return _unary(lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                  boxes, name="box_area")


def box_iou(boxes1, boxes2, name=None):
    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return nary(f, [ensure_tensor(boxes1), ensure_tensor(boxes2)],
                name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (data-dependent output size — eager only, like the
    reference's CPU fallback path)."""
    b = np.asarray(ensure_tensor(boxes)._data, dtype=np.float64)
    s = np.asarray(ensure_tensor(scores)._data) if scores is not None \
        else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(ensure_tensor(boxes_num)._data)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat, rois):
        n_rois = rois.shape[0]
        C = feat.shape[1]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        # one sample per bin center (sampling_ratio=1 equivalent)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5) / oh * rh[:, None]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5) / ow * rw[:, None]

        outs = []
        for r in range(n_rois):
            fmap = feat[batch_idx[r]]  # (C, H, W)
            yy, xx = ys[r], xs[r]
            H, W = fmap.shape[-2:]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy - y0, 0, 1)[:, None]
            wx = jnp.clip(xx - x0, 0, 1)[None, :]
            v00 = fmap[:, y0][:, :, x0]
            v01 = fmap[:, y0][:, :, x1_]
            v10 = fmap[:, y1_][:, :, x0]
            v11 = fmap[:, y1_][:, :, x1_]
            out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                   v10 * wy * (1 - wx) + v11 * wy * wx)
            outs.append(out)
        return jnp.stack(outs) if outs else jnp.zeros((0, C, oh, ow),
                                                      feat.dtype)
    return nary(f, [x, boxes], name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    # max-pool variant of roi_align with nearest binning
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(ensure_tensor(boxes_num)._data)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat, rois):
        outs = []
        H, W = feat.shape[-2:]
        for r in range(rois.shape[0]):
            fmap = feat[batch_idx[r]]
            x1 = jnp.round(rois[r, 0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(rois[r, 1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.maximum(jnp.round(rois[r, 2] * spatial_scale), x1 + 1)
            y2 = jnp.maximum(jnp.round(rois[r, 3] * spatial_scale), y1 + 1)
            ys = jnp.clip(jnp.linspace(y1, y2, oh + 1), 0, H).astype(jnp.int32)
            xs = jnp.clip(jnp.linspace(x1, x2, ow + 1), 0, W).astype(jnp.int32)
            # fixed-size gather grid (8 samples per bin edge-to-edge)
            gy = jnp.clip((ys[:-1, None] + jnp.arange(8)[None, :]), 0, H - 1)
            gx = jnp.clip((xs[:-1, None] + jnp.arange(8)[None, :]), 0, W - 1)
            patch = fmap[:, gy][:, :, :, gx]  # C, oh, 8, ow, 8
            outs.append(patch.max(axis=(2, 4)))
        return jnp.stack(outs)
    return nary(f, [x, boxes], name="roi_pool")


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    def f(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            ox = (tx - px) / pw / pbv[:, 0]
            oy = (ty - py) / ph / pbv[:, 1]
            ow = jnp.log(tw / pw) / pbv[:, 2]
            oh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([ox, oy, ow, oh], axis=1)
        ox = pbv[:, 0] * tb[..., 0] * pw + px
        oy = pbv[:, 1] * tb[..., 1] * ph + py
        ow = jnp.exp(pbv[:, 2] * tb[..., 2]) * pw
        oh = jnp.exp(pbv[:, 3] * tb[..., 3]) * ph
        return jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2,
                          oy + oh / 2], axis=-1)
    return nary(f, [ensure_tensor(prior_box), ensure_tensor(prior_box_var),
                    ensure_tensor(target_box)], name="box_coder")


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (ref ``python/paddle/vision/ops.py``
    generate_proposals → ``phi/kernels/gpu/generate_proposals_kernel.cu``).

    Host-side like :func:`nms` (data-dependent output sizes — the
    reference also emits LoD rois): per image, decode anchor deltas,
    clip to the image, drop boxes under ``min_size``, keep the
    ``pre_nms_top_n`` best, NMS, keep ``post_nms_top_n``.

    scores ``[N, A, H, W]``; bbox_deltas ``[N, 4A, H, W]``; img_size
    ``[N, 2]`` (h, w); anchors/variances ``[H, W, A, 4]``. Returns
    (rois ``[R, 4]``, roi_probs ``[R, 1]``[, rois_num ``[N]``]).
    """
    sc = np.asarray(ensure_tensor(scores)._data, np.float32)
    de = np.asarray(ensure_tensor(bbox_deltas)._data, np.float32)
    iszs = np.asarray(ensure_tensor(img_size)._data, np.float32)
    an = np.asarray(ensure_tensor(anchors)._data, np.float32).reshape(-1, 4)
    va = np.asarray(ensure_tensor(variances)._data,
                    np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    offset = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        # [A,H,W] -> [H,W,A] -> flat, matching the anchors' [H,W,A,4]
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = de[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = min(pre_nms_top_n, s.size) if pre_nms_top_n > 0 else s.size
        order = np.argsort(-s)[:k]
        s, d, a, v = s[order], d[order], an[order], va[order]
        # decode (variance-scaled center-size transform)
        aw = a[:, 2] - a[:, 0] + offset
        ah = a[:, 3] - a[:, 1] + offset
        acx = a[:, 0] + 0.5 * aw
        acy = a[:, 1] + 0.5 * ah
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        wN = np.exp(np.minimum(v[:, 2] * d[:, 2], np.log(1000. / 16.))) * aw
        hN = np.exp(np.minimum(v[:, 3] * d[:, 3], np.log(1000. / 16.))) * ah
        boxes = np.stack([cx - 0.5 * wN, cy - 0.5 * hN,
                          cx + 0.5 * wN - offset,
                          cy + 0.5 * hN - offset], axis=1)
        imh, imw = iszs[n, 0], iszs[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, imw - offset)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, imh - offset)
        ws = boxes[:, 2] - boxes[:, 0] + offset
        hs = boxes[:, 3] - boxes[:, 1] + offset
        keep = (ws >= min_size) & (hs >= min_size)
        boxes, s = boxes[keep], s[keep]
        if boxes.shape[0]:
            keep_idx = np.asarray(
                nms(Tensor(jnp.asarray(boxes)), iou_threshold=nms_thresh,
                    scores=Tensor(jnp.asarray(s)),
                    top_k=post_nms_top_n)._data)
            boxes, s = boxes[keep_idx], s[keep_idx]
        all_rois.append(boxes)
        all_probs.append(s[:, None])
        nums.append(len(boxes))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)
                              if all_rois else np.zeros((0, 4), np.float32)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)
                               if all_probs else np.zeros((0, 1),
                                                          np.float32)))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels by scale (ref ``python/paddle/vision/
    ops.py distribute_fpn_proposals``): level = floor(log2(sqrt(area) /
    refer_scale) + refer_level), clipped to [min_level, max_level].
    Returns (multi_rois per level, restore_ind[, rois_num_per_level])."""
    r = np.asarray(ensure_tensor(fpn_rois)._data, np.float32)
    offset = 1.0 if pixel_offset else 0.0
    w = r[:, 2] - r[:, 0] + offset
    h = r[:, 3] - r[:, 1] + offset
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8) + refer_level)
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)

    # image id of every roi (from the per-image counts, when batched)
    if rois_num is not None:
        counts = np.asarray(ensure_tensor(rois_num)._data,
                            np.int64).ravel()
        img_of = np.repeat(np.arange(counts.size), counts)
    else:
        counts = np.array([r.shape[0]], np.int64)
        img_of = np.zeros(r.shape[0], np.int64)

    multi_rois, lvl_nums, order = [], [], []
    for l in range(min_level, max_level + 1):
        idx = np.where(lvl == l)[0]
        order.append(idx)
        multi_rois.append(Tensor(jnp.asarray(
            r[idx] if idx.size else np.zeros((0, 4), np.float32))))
        # per-IMAGE counts at this level, shape [N] (ref semantics)
        lvl_nums.append(np.bincount(img_of[idx],
                                    minlength=counts.size).astype(np.int32))
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    restore_ind = Tensor(jnp.asarray(restore[:, None].astype(np.int32)))
    if rois_num is not None:
        return multi_rois, restore_ind, [Tensor(jnp.asarray(n))
                                         for n in lvl_nums]
    return multi_rois, restore_ind


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 head (ref ``python/paddle/vision/ops.py yolo_box``
    → ``phi/kernels/.../yolo_box_kernel``): pure jnp, jit-friendly.

    x ``[N, an*(5+class_num), H, W]``; img_size ``[N, 2]`` (h, w).
    Returns (boxes ``[N, an*H*W, 4]`` xyxy, scores ``[N, an*H*W,
    class_num]``); predictions under ``conf_thresh`` are zeroed like the
    reference.
    """
    def f(feat, im):
        an = jnp.asarray(np.asarray(anchors, np.float32).reshape(-1, 2))
        n_anchor = an.shape[0]
        N, C, H, W = feat.shape
        iou_pred = None
        if iou_aware:
            # PP-YOLO layout: [N, an + an*(5+cls), H, W] — the per-anchor
            # IoU logits come first (ref yolo_box kernel entry_index)
            iou_pred = jax.nn.sigmoid(feat[:, :n_anchor])  # [N, an, H, W]
            feat = feat[:, n_anchor:]
        p = feat.reshape(N, n_anchor, 5 + class_num, H, W)
        p = jnp.moveaxis(p, 2, -1)            # [N, an, H, W, 5+cls]
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        alpha = scale_x_y
        beta = -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(p[..., 0]) * alpha + beta + gx) / W
        cy = (jax.nn.sigmoid(p[..., 1]) * alpha + beta + gy) / H
        input_w = jnp.float32(downsample_ratio * W)
        input_h = jnp.float32(downsample_ratio * H)
        bw = jnp.exp(p[..., 2]) * an[None, :, None, None, 0] / input_w
        bh = jnp.exp(p[..., 3]) * an[None, :, None, None, 1] / input_h
        conf = jax.nn.sigmoid(p[..., 4])
        if iou_pred is not None:
            # IoU-aware rescoring: conf^(1-f) * iou^f
            conf = conf ** (1.0 - iou_aware_factor) * \
                iou_pred ** iou_aware_factor
        cls = jax.nn.sigmoid(p[..., 5:]) * conf[..., None]
        imh = im[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = im[:, 1].astype(jnp.float32)[:, None, None, None]
        x0 = (cx - bw / 2) * imw
        y0 = (cy - bh / 2) * imh
        x1 = (cx + bw / 2) * imw
        y1 = (cy + bh / 2) * imh
        if clip_bbox:
            x0 = jnp.clip(x0, 0, imw - 1)
            y0 = jnp.clip(y0, 0, imh - 1)
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1)
        mask = (conf >= conf_thresh).astype(boxes.dtype)
        boxes = boxes * mask[..., None]
        cls = cls * mask[..., None]
        return (boxes.reshape(N, -1, 4),
                cls.reshape(N, -1, class_num))

    return nary(f, [ensure_tensor(x), ensure_tensor(img_size)],
                name="yolo_box", n_out=2)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (ref ``python/paddle/vision/ops.py yolo_loss``
    → ``phi/kernels/.../yolov3_loss_kernel``), one head.

    Pure jnp re-design: responsibility assignment (best shape-IoU anchor
    per gt) and target construction are scatters with out-of-bounds
    drops for invalid/other-head gts; the ignore mask comes from a dense
    pred-vs-gt IoU. Returns the per-sample loss ``[N]``.

    x ``[N, len(anchor_mask)*(5+class_num), H, W]``; gt_box ``[N, B, 4]``
    (cx, cy, w, h, normalized to the image); gt_label ``[N, B]`` int
    (boxes with ``w <= 0`` are padding).
    """
    anchors_np = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_np = np.asarray(anchor_mask, np.int64)
    A = len(mask_np)

    def f(feat, gtb, gtl, *rest):
        gsc = rest[0] if gt_score is not None else None
        N, C, H, W = feat.shape
        B = gtb.shape[1]
        input_h = jnp.float32(downsample_ratio * H)
        input_w = jnp.float32(downsample_ratio * W)
        p = feat.reshape(N, A, 5 + class_num, H, W)
        p = jnp.moveaxis(p, 2, -1)  # [N, A, H, W, 5+cls]
        tx, ty, tw, th = p[..., 0], p[..., 1], p[..., 2], p[..., 3]
        tobj, tcls = p[..., 4], p[..., 5:]

        an_all = jnp.asarray(anchors_np)          # [An, 2] pixels
        an_head = an_all[jnp.asarray(mask_np)]    # [A, 2]

        # -- decode predicted boxes (relative units) for the ignore mask
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        bx = (jax.nn.sigmoid(tx) * alpha + beta + gx) / W
        by = (jax.nn.sigmoid(ty) * alpha + beta + gy) / H
        bw = jnp.exp(tw) * an_head[None, :, None, None, 0] / input_w
        bh = jnp.exp(th) * an_head[None, :, None, None, 1] / input_h

        # ref GtValid (yolo_loss_kernel.cc:163): BOTH dims must be > 0
        valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)  # [N, B]

        def iou_centerwh(ax, ay, aw, ah, bx_, by_, bw_, bh_):
            x0 = jnp.maximum(ax - aw / 2, bx_ - bw_ / 2)
            x1 = jnp.minimum(ax + aw / 2, bx_ + bw_ / 2)
            y0 = jnp.maximum(ay - ah / 2, by_ - bh_ / 2)
            y1 = jnp.minimum(ay + ah / 2, by_ + bh_ / 2)
            inter = jnp.clip(x1 - x0, 0) * jnp.clip(y1 - y0, 0)
            union = aw * ah + bw_ * bh_ - inter
            return inter / jnp.maximum(union, 1e-10)

        # best IoU of each prediction against any valid gt: [N,A,H,W]
        iou_pg = iou_centerwh(
            bx[..., None], by[..., None], bw[..., None], bh[..., None],
            gtb[:, None, None, None, :, 0], gtb[:, None, None, None, :, 1],
            gtb[:, None, None, None, :, 2], gtb[:, None, None, None, :, 3])
        iou_pg = jnp.where(valid[:, None, None, None, :], iou_pg, 0.0)
        ignore = iou_pg.max(-1) > ignore_thresh

        # -- responsibility: best shape-IoU over the FULL anchor set
        gw_pix = gtb[..., 2] * input_w
        gh_pix = gtb[..., 3] * input_h
        inter = (jnp.minimum(gw_pix[..., None], an_all[None, None, :, 0])
                 * jnp.minimum(gh_pix[..., None], an_all[None, None, :, 1]))
        union = (gw_pix[..., None] * gh_pix[..., None]
                 + an_all[None, None, :, 0] * an_all[None, None, :, 1]
                 - inter)
        best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)
        # slot of that anchor within THIS head's mask (-1 -> other head)
        slot_of = jnp.full((int(an_all.shape[0]),), -1, jnp.int32)
        slot_of = slot_of.at[jnp.asarray(mask_np)].set(
            jnp.arange(A, dtype=jnp.int32))
        slot = slot_of[best_anchor]               # [N, B]
        gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)
        owns = valid & (slot >= 0)
        # OOB slot index => scatter dropped
        slot_s = jnp.where(owns, slot, A + 1)

        nidx = jnp.broadcast_to(jnp.arange(N)[:, None], (N, B))
        score = (gsc if gsc is not None
                 else jnp.ones((N, B), jnp.float32))
        box_w = score * (2.0 - gtb[..., 2] * gtb[..., 3])  # small-box boost

        def bce(logit, target):
            return (jnp.maximum(logit, 0) - logit * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logit))))

        # -- per-GT location/class losses, GATHERED at the responsible
        # cell (like the reference's per-gt loop, kernel.cc:328,346 —
        # two gts sharing a cell both contribute; no scatter collapse)
        slot_g = jnp.clip(slot, 0, A - 1)
        ow = owns.astype(jnp.float32)

        def gather(t):
            return t[nidx, slot_g, gj, gi]        # [N, B]

        txt = gtb[..., 0] * W - gi
        tyt = gtb[..., 1] * H - gj
        twt = jnp.log(jnp.maximum(
            gw_pix / jnp.maximum(an_all[best_anchor][..., 0], 1e-6), 1e-6))
        tht = jnp.log(jnp.maximum(
            gh_pix / jnp.maximum(an_all[best_anchor][..., 1], 1e-6), 1e-6))
        # ref CalcBoxLocationLoss: SCE on x/y, L1 on w/h
        loc_b = (bce(gather(tx), txt) + bce(gather(ty), tyt)
                 + jnp.abs(gather(tw) - twt) + jnp.abs(gather(th) - tht))
        loss_loc = (ow * box_w * loc_b).sum(-1)

        onehot = jax.nn.one_hot(gtl.astype(jnp.int32), class_num)
        if use_label_smooth:
            # ref kernel: delta = min(1/class_num, 1/40); pos 1-delta,
            # neg delta
            delta = min(1.0 / max(class_num, 1), 1.0 / 40.0)
            onehot = onehot * (1.0 - delta) + (1.0 - onehot) * delta
        cls_logits = tcls[nidx, slot_g, gj, gi]   # [N, B, cls]
        # ref CalcLabelLoss: per-class SCE weighted by the mixup score
        loss_cls = (ow * score * bce(cls_logits, onehot).sum(-1)).sum(-1)

        # -- objectness per cell: target 1 at responsible cells WEIGHTED
        # by the mixup score (ref kernel.cc:148 obj_mask = score), 0
        # elsewhere except ignored cells
        def scat(values):
            buf = jnp.zeros((N, A, H, W), jnp.float32)
            return buf.at[nidx, slot_s, gj, gi].set(values)

        pos = scat(jnp.ones((N, B), jnp.float32))
        obj_w = scat(score)
        # ref CalcObjnessLoss: obj > 1e-5 → positive weighted by the
        # score; obj == 0 (incl. a responsible cell whose mixup score is
        # ~0) → negative; ignored (-1) cells contribute nothing
        pos_eff = pos * (obj_w > 1e-5).astype(jnp.float32)
        neg = ((1.0 - pos) * (~ignore).astype(jnp.float32)
               + pos * (obj_w <= 1e-5).astype(jnp.float32))
        loss_obj = (pos_eff * obj_w * bce(tobj, jnp.ones_like(tobj))
                    + neg * bce(tobj, jnp.zeros_like(tobj))).sum((1, 2, 3))
        return loss_loc + loss_obj + loss_cls

    args = [ensure_tensor(x), ensure_tensor(gt_box), ensure_tensor(gt_label)]
    if gt_score is not None:
        args.append(ensure_tensor(gt_score))
    return nary(f, args, name="yolo_loss")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (ref ``python/paddle/vision/ops.py
    deform_conv2d`` → ``phi/kernels/.../deformable_conv_kernel``).

    TPU-native: one gather-based bilinear sampling of all kernel taps
    (the "deformed im2col") followed by one einsum with the weight — XLA
    maps both onto gathers + the MXU instead of a custom CUDA kernel.
    ``mask`` (modulated, v2) multiplies the sampled values.

    x ``[N, Cin, H, W]``; offset ``[N, 2*dg*kh*kw, Hout, Wout]`` ordered
    (dy, dx) per tap; weight ``[Cout, Cin/groups, kh, kw]``.
    """
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)

    def f(xd, off, w, *rest):
        it = iter(rest)
        b = next(it) if bias is not None else None
        m = next(it) if mask is not None else None
        N, Cin, H, W = xd.shape
        Cout, Cin_g, kh, kw = w.shape
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        dg = deformable_groups
        K = kh * kw
        off = off.reshape(N, dg, K, 2, Ho, Wo)

        # base sampling grid per tap: [K, Ho, Wo]
        oy = jnp.arange(Ho) * sh - ph
        ox = jnp.arange(Wo) * sw - pw
        ky, kx = jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw,
                              indexing="ij")
        base_y = ky.reshape(K, 1, 1) + oy[None, :, None]
        base_x = kx.reshape(K, 1, 1) + ox[None, None, :]
        # deformed positions: [N, dg, K, Ho, Wo]
        py = base_y[None, None] + off[:, :, :, 0]
        px = base_x[None, None] + off[:, :, :, 1]

        # bilinear sample with zero padding outside
        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = py - y0
        wx = px - x0

        def tap(yi, xi):
            inside = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            # per deformable group, gather its channel slice
            cg = Cin // dg
            xg = xd.reshape(N, dg, cg, H, W)
            # vals[n, g, c, k, i, j] = xg[n, g, c, yc[n,g,k,i,j], xc[...]]
            flat = xg.reshape(N, dg, cg, H * W)
            idx = (yc * W + xc).reshape(N, dg, 1, -1)
            got = jnp.take_along_axis(
                flat, jnp.broadcast_to(idx, (N, dg, cg, idx.shape[-1])),
                axis=3)
            got = got.reshape(N, dg, cg, K, Ho, Wo)
            return got * inside[:, :, None].astype(got.dtype)

        v = (tap(y0, x0) * ((1 - wy) * (1 - wx))[:, :, None]
             + tap(y0, x0 + 1) * ((1 - wy) * wx)[:, :, None]
             + tap(y0 + 1, x0) * (wy * (1 - wx))[:, :, None]
             + tap(y0 + 1, x0 + 1) * (wy * wx)[:, :, None])
        if m is not None:
            mm = m.reshape(N, dg, 1, K, Ho, Wo)
            v = v * mm
        # [N, Cin, K, Ho, Wo] -> grouped einsum with weight
        v = v.reshape(N, Cin, kh, kw, Ho, Wo)
        v = v.reshape(N, groups, Cin // groups, kh, kw, Ho, Wo)
        wg = w.reshape(groups, Cout // groups, Cin_g, kh, kw)
        out = jnp.einsum("ngcrsij,gocrs->ngoij", v, wg)
        out = out.reshape(N, Cout, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    args = [ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    if mask is not None:
        args.append(ensure_tensor(mask))
    return nary(f, args, name="deform_conv2d")


class DeformConv2D(Layer):
    """Layer wrapper over :func:`deform_conv2d` (ref vision/ops.py
    DeformConv2D) — a real Layer so its parameters register with
    ``parameters()``/``state_dict()`` and follow the framework RNG."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I
        kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        fan_in = in_channels // groups * kh * kw
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr, default_initializer=I.Uniform(-bound, bound))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             mask=mask, **self._cfg)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (ref ``python/paddle/vision/ops.py
    psroi_pool`` → R-FCN): input channels are ``C_out * ph * pw``; output
    bin (i, j) of class-channel c average-pools ITS OWN channel slice
    ``c*ph*pw + i*pw + j`` over the bin's integer window. Pure jnp
    (bin-membership masks + one einsum), so it is differentiable and
    jit-compatible like :func:`roi_align`."""
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    bn = np.asarray(ensure_tensor(boxes_num)._data)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def f(feat, rois):
        N, C, H, W = feat.shape
        Co = C // (ph * pw)
        # channel owned by (class c, bin i, bin j)
        ch_idx = (np.arange(Co)[:, None, None] * ph * pw
                  + np.arange(ph)[None, :, None] * pw
                  + np.arange(pw)[None, None, :])  # [Co, ph, pw]
        outs = []
        for r in range(rois.shape[0]):
            fmap = feat[batch_idx[r]].astype(jnp.float32)
            x0 = rois[r, 0] * spatial_scale
            y0 = rois[r, 1] * spatial_scale
            x1 = rois[r, 2] * spatial_scale
            y1 = rois[r, 3] * spatial_scale
            rh = jnp.maximum(y1 - y0, 0.1) / ph
            rw = jnp.maximum(x1 - x0, 0.1) / pw
            hh = jnp.arange(H, dtype=jnp.float32)
            ww = jnp.arange(W, dtype=jnp.float32)
            i_ = jnp.arange(ph, dtype=jnp.float32)[:, None]
            j_ = jnp.arange(pw, dtype=jnp.float32)[:, None]
            # integer windows [floor(start), ceil(end)) per bin
            my = ((hh[None, :] >= jnp.floor(y0 + i_ * rh))
                  & (hh[None, :] < jnp.ceil(y0 + (i_ + 1) * rh))
                  & (hh[None, :] >= 0)).astype(jnp.float32)   # [ph, H]
            mx = ((ww[None, :] >= jnp.floor(x0 + j_ * rw))
                  & (ww[None, :] < jnp.ceil(x0 + (j_ + 1) * rw))
                  & (ww[None, :] >= 0)).astype(jnp.float32)   # [pw, W]
            counts = my.sum(1)[:, None] * mx.sum(1)[None, :]  # [ph, pw]
            # gather each bin's OWN channel first ([Co,ph,pw,H,W]) so the
            # reduction touches only the kept slices, not all C channels
            sel = fmap[ch_idx]
            sums = jnp.einsum("cijhw,ih,jw->cij", sel, my, mx)
            outs.append(sums / jnp.maximum(counts, 1.0)[None])
        return (jnp.stack(outs).astype(feat.dtype) if outs
                else jnp.zeros((0, Co, ph, pw), feat.dtype))

    return nary(f, [ensure_tensor(x), ensure_tensor(boxes)],
                name="psroi_pool")


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self._size = output_size
        self._scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._size, self._scale)


def read_file(filename, name=None):
    """ref ``vision/ops.py read_file``: raw file bytes as a 1-D uint8
    Tensor (pair with :func:`decode_jpeg`)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """ref ``vision/ops.py decode_jpeg`` (CPU/GPU jpeg decoder). Decodes
    a 1-D uint8 byte Tensor into CHW uint8 via PIL — host-side, like the
    reference's CPU path; TPU consumes the decoded array."""
    import io
    from PIL import Image

    buf = bytes(np.asarray(ensure_tensor(x)._data, np.uint8))
    img = Image.open(io.BytesIO(buf))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(np.ascontiguousarray(arr))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) boxes (ref ``vision/ops.py prior_box``):
    returns (boxes [H, W, P, 4], variances [H, W, P, 4]) — pure anchor
    arithmetic, computed once and traced as constants by XLA."""
    input = ensure_tensor(input)
    image = ensure_tensor(image)
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []  # per-prior (w, h) in pixels
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                big = np.sqrt(ms * float(max_sizes[k]))
                whs.append((big, big))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                big = np.sqrt(ms * float(max_sizes[k]))
                whs.append((big, big))
    whs = np.asarray(whs, np.float32)  # [P, 2]

    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)  # [H, W]
    P = whs.shape[0]
    boxes = np.empty((fh, fw, P, 4), np.float32)
    boxes[..., 0] = (cxg[:, :, None] - whs[None, None, :, 0] / 2) / iw
    boxes[..., 1] = (cyg[:, :, None] - whs[None, None, :, 1] / 2) / ih
    boxes[..., 2] = (cxg[:, :, None] + whs[None, None, :, 0] / 2) / iw
    boxes[..., 3] = (cyg[:, :, None] + whs[None, None, :, 1] / 2) / ih
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            boxes.shape).copy()
    return Tensor(boxes), Tensor(vars_)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (ref ``vision/ops.py matrix_nms``): soft suppression
    with a pairwise-IoU decay matrix instead of hard pruning. Host-side
    (data-dependent output count, a stream-sync in the reference too)."""
    b = np.asarray(ensure_tensor(bboxes)._data)
    s = np.asarray(ensure_tensor(scores)._data)
    N, C, M = s.shape

    def iou_matrix(boxes):
        x1, y1, x2, y2 = boxes.T
        off = 0.0 if normalized else 1.0
        area = (x2 - x1 + off) * (y2 - y1 + off)
        ix1 = np.maximum(x1[:, None], x1[None, :])
        iy1 = np.maximum(y1[:, None], y1[None, :])
        ix2 = np.minimum(x2[:, None], x2[None, :])
        iy2 = np.minimum(y2[:, None], y2[None, :])
        iw = np.maximum(ix2 - ix1 + off, 0)
        ih = np.maximum(iy2 - iy1 + off, 0)
        inter = iw * ih
        return inter / np.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)

    out_rows, out_idx, rois_num = [], [], []
    for n in range(N):
        rows = []
        for c in range(C):
            if c == background_label:
                continue
            sc = s[n, c]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])]
            if nms_top_k > -1:
                order = order[:nms_top_k]
            boxes = b[n, order]
            scs = sc[order]
            ious = np.triu(iou_matrix(boxes), k=1)
            # decay_j = min_{i<j} f(iou_ij) / f(iou_cmax_i), where
            # iou_cmax_i is suppressor i's own max overlap with boxes
            # scored above IT (row-indexed denominator)
            iou_cmax = ious.max(axis=0)  # per box: overlap w/ higher
            if use_gaussian:
                decay = np.exp(-(ious ** 2 - iou_cmax[:, None] ** 2)
                               * gaussian_sigma)
            else:
                decay = (1 - ious) / np.maximum(1 - iou_cmax[:, None],
                                                1e-10)
            decay = np.where(np.triu(np.ones_like(ious), k=1) > 0,
                             decay, np.inf)
            decay = np.minimum(decay.min(axis=0), 1.0)
            dec_scores = scs * decay
            sel = dec_scores > post_threshold
            for j in np.where(sel)[0]:
                rows.append((c, dec_scores[j], *b[n, order[j]],
                             n * M + order[j]))
        rows.sort(key=lambda r: -r[1])
        if keep_top_k > -1:
            rows = rows[:keep_top_k]
        rois_num.append(len(rows))
        out_rows.extend(r[:6] for r in rows)
        out_idx.extend(r[6] for r in rows)

    out = (np.asarray(out_rows, np.float32) if out_rows
           else np.zeros((0, 6), np.float32))
    ret = [Tensor(out)]
    if return_index:
        ret.append(Tensor(np.asarray(out_idx, np.int64).reshape(-1, 1)))
    if return_rois_num:
        ret.append(Tensor(np.asarray(rois_num, np.int32)))
    return tuple(ret) if len(ret) > 1 else ret[0]


class ConvNormActivation(object):
    """Built lazily to avoid importing nn at module import; see
    ``paddle_tpu.vision.models`` blocks for the pattern (ref
    ``vision/ops.py ConvNormActivation``)."""

    _DEFAULT = object()  # sentinel: None means "omit this layer" (ref)

    def __new__(cls, in_channels, out_channels, kernel_size=3, stride=1,
                padding=None, groups=1, norm_layer=_DEFAULT,
                activation_layer=_DEFAULT, dilation=1, bias=None):
        from .. import nn
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if norm_layer is cls._DEFAULT:
            norm_layer = nn.BatchNorm2D
        if activation_layer is cls._DEFAULT:
            activation_layer = nn.ReLU
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                            padding, dilation=dilation, groups=groups,
                            bias_attr=bias if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        return nn.Sequential(*layers)
