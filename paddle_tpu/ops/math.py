"""Math ops: elementwise, reductions, cast, clip.

Ref: ``python/paddle/tensor/math.py`` (and the kernels they dispatch to under
``paddle/phi/kernels``). On TPU each op lowers to one XLA HLO op; elementwise
chains fuse automatically, so there is no fused-kernel zoo to maintain.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework.dtype import to_jax_dtype
from .op_utils import ensure_tensor, unary as _unary, binary as _binary, nary

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "float_power", "maximum", "minimum", "fmax", "fmin",
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt",
    "square", "abs", "sign", "sgn", "floor", "ceil", "round", "trunc", "frac",
    "reciprocal", "neg", "sin", "cos", "tan", "asin", "acos", "atan", "sinh",
    "cosh", "tanh", "asinh", "acosh", "atanh", "atan2", "deg2rad", "rad2deg",
    "erf", "erfinv", "lgamma", "digamma", "logit", "sigmoid", "expit",
    "sum", "mean", "max", "min", "prod", "amax", "amin", "nansum", "nanmean",
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp", "logsumexp",
    "clip", "cast", "isnan", "isinf", "isfinite", "nan_to_num",
    "all", "any", "heaviside", "gcd", "lcm", "kron", "trace", "diagonal",
    "angle", "conj", "real", "imag", "lerp", "rot90", "median", "nanmedian",
    "quantile", "nanquantile", "std", "var", "count_nonzero", "add_n",
    "inner", "outer", "stanh", "scale", "increment", "log_normalize",
    "renorm", "take", "frexp", "ldexp", "hypot", "nextafter", "copysign",
    "i0", "i0e", "i1", "i1e", "polygamma", "multiply_", "add_", "subtract_",
    "divide_", "clip_", "scale_", "floor_", "ceil_", "exp_", "sqrt_",
    "reciprocal_", "round_", "rsqrt_", "sigmoid_", "tanh_", "logaddexp",
    "floor_mod", "pow_", "addmm", "addmm_", "diff", "trapezoid",
    "cumulative_trapezoid", "vander", "multiplex", "broadcast_shape",
]


# ---- binary elementwise ---------------------------------------------------
def add(x, y, name=None):
    return _binary(jnp.add, x, y, name="add")


def subtract(x, y, name=None):
    return _binary(jnp.subtract, x, y, name="subtract")


def multiply(x, y, name=None):
    return _binary(jnp.multiply, x, y, name="multiply")


def divide(x, y, name=None):
    return _binary(jnp.true_divide, x, y, name="divide")


def floor_divide(x, y, name=None):
    return _binary(jnp.floor_divide, x, y, name="floor_divide")


def mod(x, y, name=None):
    return _binary(jnp.mod, x, y, name="mod")


remainder = mod


def pow(x, y, name=None):
    return _binary(jnp.power, x, y, name="pow")


def float_power(x, y, name=None):
    return _binary(lambda a, b: jnp.power(a.astype(jnp.float32),
                                          b.astype(jnp.float32)), x, y,
                   name="float_power")


def maximum(x, y, name=None):
    return _binary(jnp.maximum, x, y, name="maximum")


def minimum(x, y, name=None):
    return _binary(jnp.minimum, x, y, name="minimum")


def fmax(x, y, name=None):
    return _binary(jnp.fmax, x, y, name="fmax")


def fmin(x, y, name=None):
    return _binary(jnp.fmin, x, y, name="fmin")


def atan2(x, y, name=None):
    return _binary(jnp.arctan2, x, y, name="atan2")


def heaviside(x, y, name=None):
    return _binary(jnp.heaviside, x, y, name="heaviside")


def gcd(x, y, name=None):
    return _binary(jnp.gcd, x, y, name="gcd")


def lcm(x, y, name=None):
    return _binary(jnp.lcm, x, y, name="lcm")


def logaddexp(x, y, name=None):
    return _binary(jnp.logaddexp, x, y, name="logaddexp")


def hypot(x, y, name=None):
    return _binary(jnp.hypot, x, y, name="hypot")


def nextafter(x, y, name=None):
    return _binary(jnp.nextafter, x, y, name="nextafter")


def copysign(x, y, name=None):
    return _binary(jnp.copysign, x, y, name="copysign")


def lerp(x, y, weight, name=None):
    return nary(lambda a, b, w: a + w * (b - a), [x, y, weight], name="lerp")


def kron(x, y, name=None):
    return _binary(jnp.kron, x, y, name="kron")


def inner(x, y, name=None):
    return _binary(jnp.inner, x, y, name="inner")


def outer(x, y, name=None):
    return _binary(lambda a, b: jnp.outer(a, b), x, y, name="outer")


# ---- unary elementwise ----------------------------------------------------
def _make_unary(jfn, opname):
    def op(x, name=None):
        return _unary(jfn, x, name=opname)
    op.__name__ = opname
    return op


exp = _make_unary(jnp.exp, "exp")
expm1 = _make_unary(jnp.expm1, "expm1")
log = _make_unary(jnp.log, "log")
log2 = _make_unary(jnp.log2, "log2")
log10 = _make_unary(jnp.log10, "log10")
log1p = _make_unary(jnp.log1p, "log1p")
sqrt = _make_unary(jnp.sqrt, "sqrt")
rsqrt = _make_unary(jax.lax.rsqrt, "rsqrt")
square = _make_unary(jnp.square, "square")
abs = _make_unary(jnp.abs, "abs")
sign = _make_unary(jnp.sign, "sign")
sgn = sign
floor = _make_unary(jnp.floor, "floor")
ceil = _make_unary(jnp.ceil, "ceil")
trunc = _make_unary(jnp.trunc, "trunc")
reciprocal = _make_unary(jnp.reciprocal, "reciprocal")
neg = _make_unary(jnp.negative, "neg")
sin = _make_unary(jnp.sin, "sin")
cos = _make_unary(jnp.cos, "cos")
tan = _make_unary(jnp.tan, "tan")
asin = _make_unary(jnp.arcsin, "asin")
acos = _make_unary(jnp.arccos, "acos")
atan = _make_unary(jnp.arctan, "atan")
sinh = _make_unary(jnp.sinh, "sinh")
cosh = _make_unary(jnp.cosh, "cosh")
tanh = _make_unary(jnp.tanh, "tanh")
asinh = _make_unary(jnp.arcsinh, "asinh")
acosh = _make_unary(jnp.arccosh, "acosh")
atanh = _make_unary(jnp.arctanh, "atanh")
deg2rad = _make_unary(jnp.deg2rad, "deg2rad")
rad2deg = _make_unary(jnp.rad2deg, "rad2deg")
erf = _make_unary(jax.scipy.special.erf, "erf")
erfinv = _make_unary(jax.scipy.special.erfinv, "erfinv")
lgamma = _make_unary(jax.scipy.special.gammaln, "lgamma")
digamma = _make_unary(jax.scipy.special.digamma, "digamma")
sigmoid = _make_unary(jax.nn.sigmoid, "sigmoid")
expit = sigmoid
angle = _make_unary(jnp.angle, "angle")
conj = _make_unary(jnp.conj, "conj")
real = _make_unary(jnp.real, "real")
imag = _make_unary(jnp.imag, "imag")
i0 = _make_unary(jax.scipy.special.i0, "i0")
i0e = _make_unary(jax.scipy.special.i0e, "i0e")
i1 = _make_unary(jax.scipy.special.i1, "i1")
i1e = _make_unary(jax.scipy.special.i1e, "i1e")


def polygamma(x, n, name=None):
    return _unary(lambda d: jax.scipy.special.polygamma(n, d), x,
                  name="polygamma")


def round(x, decimals=0, name=None):
    return _unary(lambda d: jnp.round(d, decimals), x, name="round")


def frac(x, name=None):
    return _unary(lambda d: d - jnp.trunc(d), x, name="frac")


def logit(x, eps=None, name=None):
    def f(d):
        z = jnp.clip(d, eps, 1 - eps) if eps else d
        return jnp.log(z) - jnp.log1p(-z)
    return _unary(f, x, name="logit")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary(lambda d: scale_b * jnp.tanh(scale_a * d), x, name="stanh")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(d):
        out = d * scale + bias if bias_after_scale else (d + bias) * scale
        return out
    out = _unary(f, x, name="scale")
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def frexp(x, name=None):
    x = ensure_tensor(x)
    m, e = jnp.frexp(x._data)
    return Tensor(m), Tensor(e)


def ldexp(x, y, name=None):
    return _binary(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), x, y,
                   name="ldexp")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _unary(lambda d: jnp.nan_to_num(d, nan=nan, posinf=posinf,
                                           neginf=neginf), x, name="nan_to_num")


def isnan(x, name=None):
    return _unary(jnp.isnan, x, name="isnan")


def isinf(x, name=None):
    return _unary(jnp.isinf, x, name="isinf")


def isfinite(x, name=None):
    return _unary(jnp.isfinite, x, name="isfinite")


def clip(x, min=None, max=None, name=None):
    # tensor bounds stay on device: jnp.clip broadcasts 0-d arrays, and
    # .item() here would stall the pipeline (and break under jit)
    mn = min._data if isinstance(min, Tensor) else min
    mx = max._data if isinstance(max, Tensor) else max
    return _unary(lambda d: jnp.clip(d, mn, mx), x, name="clip")


def cast(x, dtype):
    x = ensure_tensor(x)
    dt = to_jax_dtype(dtype)
    if x._data.dtype == dt:
        return x
    return _unary(lambda d: d.astype(dt), x, name="cast")


# ---- reductions -----------------------------------------------------------
def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.numpy().tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _make_reduce(jfn, opname, bool_to_int=False):
    def op(x, axis=None, keepdim=False, name=None):
        x = ensure_tensor(x)
        ax = _norm_axis(axis)

        def f(d):
            if bool_to_int and d.dtype == jnp.bool_:
                d = d.astype(jnp.int32)
            return jfn(d, axis=ax, keepdims=keepdim)
        return _unary(f, x, name=opname)
    op.__name__ = opname
    return op


sum = _make_reduce(jnp.sum, "sum", bool_to_int=True)
nansum = _make_reduce(jnp.nansum, "nansum")
mean = _make_reduce(jnp.mean, "mean")
nanmean = _make_reduce(jnp.nanmean, "nanmean")
max = _make_reduce(jnp.max, "max")
min = _make_reduce(jnp.min, "min")
amax = _make_reduce(jnp.max, "amax")
amin = _make_reduce(jnp.min, "amin")
prod = _make_reduce(jnp.prod, "prod")
all = _make_reduce(jnp.all, "all")
any = _make_reduce(jnp.any, "any")


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return _unary(lambda d: jax.scipy.special.logsumexp(d, axis=ax,
                                                        keepdims=keepdim),
                  x, name="logsumexp")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return _unary(lambda d: jnp.count_nonzero(d, axis=ax, keepdims=keepdim)
                  .astype(jnp.int32), x, name="count_nonzero")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return _unary(lambda d: jnp.std(d, axis=ax, ddof=1 if unbiased else 0,
                                    keepdims=keepdim), x, name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return _unary(lambda d: jnp.var(d, axis=ax, ddof=1 if unbiased else 0,
                                    keepdims=keepdim), x, name="var")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _norm_axis(axis)
    if mode == "avg":
        return _unary(lambda d: jnp.median(d, axis=ax, keepdims=keepdim), x,
                      name="median")
    # min mode: lower median
    def f(d):
        n = d.shape[ax] if ax is not None else d.size
        k = (n - 1) // 2
        s = jnp.sort(d, axis=ax) if ax is not None else jnp.sort(d.ravel())
        return jnp.take(s, k, axis=ax if ax is not None else 0)
    return _unary(f, x, name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return _unary(lambda d: jnp.nanmedian(d, axis=ax, keepdims=keepdim), x,
                  name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _norm_axis(axis)
    qv = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    return _unary(lambda d: jnp.quantile(d.astype(jnp.float32), qv, axis=ax,
                                         keepdims=keepdim, method=interpolation),
                  x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    qv = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    return _unary(lambda d: jnp.nanquantile(d.astype(jnp.float32), qv, axis=ax,
                                            keepdims=keepdim), x,
                  name="nanquantile")


# ---- scans ----------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype is not None else None
    def f(d):
        if axis is None:
            return jnp.cumsum(d.ravel(), dtype=dt)
        return jnp.cumsum(d, axis=axis, dtype=dt)
    return _unary(f, x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype is not None else None
    def f(d):
        if dim is None:
            return jnp.cumprod(d.ravel(), dtype=dt)
        return jnp.cumprod(d, axis=dim, dtype=dt)
    return _unary(f, x, name="cumprod")


def _cum_extremum(x, axis, dtype, better):
    """(values, indices) running extremum via one associative scan over
    (value, index) pairs — O(n log n) depth, no O(n^2) blowup. Index of the
    FIRST occurrence among ties (matches the reference kernel)."""
    x = ensure_tensor(x)
    ax = 0 if axis is None else axis
    d = x._data.ravel() if axis is None else x._data
    ax = ax % d.ndim
    shape = [1] * d.ndim
    shape[ax] = d.shape[ax]
    idx0 = jnp.broadcast_to(
        jnp.arange(d.shape[ax]).reshape(shape), d.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        # b is later in scan order; keep a on ties (first occurrence)
        pick_b = better(bv, av)
        return jnp.where(pick_b, bv, av), jnp.where(pick_b, bi, ai)

    vals, idx = jax.lax.associative_scan(combine, (d, idx0), axis=ax)
    return Tensor(vals), Tensor(idx.astype(to_jax_dtype(dtype)))


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extremum(x, axis, dtype, lambda b, a: b > a)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extremum(x, axis, dtype, lambda b, a: b < a)


def logcumsumexp(x, axis=None, name=None):
    def f(d):
        dd = d.ravel() if axis is None else d
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, dd, axis=ax)
    return _unary(f, x, name="logcumsumexp")


# ---- matrix-ish helpers kept in math for paddle-parity --------------------
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _unary(lambda d: jnp.trace(d, offset=offset, axis1=axis1,
                                      axis2=axis2), x, name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _unary(lambda d: jnp.diagonal(d, offset=offset, axis1=axis1,
                                         axis2=axis2), x, name="diagonal")


def rot90(x, k=1, axes=(0, 1), name=None):
    return _unary(lambda d: jnp.rot90(d, k=k, axes=tuple(axes)), x,
                  name="rot90")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return nary(lambda *ds: jnp.sum(jnp.stack(
        [d.astype(jnp.result_type(*[dd.dtype for dd in ds])) for d in ds]),
        axis=0), list(inputs), name="add_n")


def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


def log_normalize(x, axis=-1, name=None):
    return _unary(lambda d: d - jax.scipy.special.logsumexp(
        d, axis=axis, keepdims=True), x, name="log_normalize")


def renorm(x, p, axis, max_norm, name=None):
    def f(d):
        dims = [i for i in range(d.ndim) if i != axis % d.ndim]
        norms = jnp.sum(jnp.abs(d) ** p, axis=tuple(dims), keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return d * factor
    return _unary(f, x, name="renorm")


def take(x, index, mode="raise", name=None):
    return nary(lambda d, i: jnp.take(d.ravel(), i.ravel(),
                                      mode="clip" if mode != "wrap" else "wrap"
                                      ).reshape(jnp.shape(i)),
                [x, ensure_tensor(index)], name="take")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """``beta*input + alpha*(x @ y)`` (ref: ``tensor/math.py addmm``) —
    one fused XLA dot+axpy, MXU-shaped."""
    return nary(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                [input, x, y], name="addmm")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    """n-th forward difference along ``axis`` (ref: ``tensor/math.py
    diff``)."""
    args = [x]
    has_pre = prepend is not None
    has_app = append is not None
    if has_pre:
        args.append(ensure_tensor(prepend))
    if has_app:
        args.append(ensure_tensor(append))

    def f(d, *extra):
        pre = extra[0] if has_pre else None
        app = extra[-1] if has_app else None
        return jnp.diff(d, n=n, axis=axis, prepend=pre, append=app)

    return nary(f, args, name="diff")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal-rule integral (ref: ``tensor/math.py trapezoid``)."""
    if x is not None and dx is not None:
        raise ValueError("Not permitted to provide x and dx input together.")
    if x is not None:
        return nary(lambda yd, xd: jax.scipy.integrate.trapezoid(
            yd, x=xd, axis=axis), [y, ensure_tensor(x)], name="trapezoid")
    step = 1.0 if dx is None else dx
    return _unary(lambda yd: jax.scipy.integrate.trapezoid(
        yd, dx=step, axis=axis), y, name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoidal integral (ref: ``tensor/math.py
    cumulative_trapezoid``): output has size-1 shorter ``axis``."""
    if x is not None and dx is not None:
        raise ValueError("Not permitted to provide x and dx input together.")

    def _cum(yd, spacing):
        lo = jax.lax.slice_in_dim(yd, 0, yd.shape[axis] - 1, axis=axis)
        hi = jax.lax.slice_in_dim(yd, 1, yd.shape[axis], axis=axis)
        return jnp.cumsum((lo + hi) * 0.5 * spacing, axis=axis)

    if x is not None:
        def f(yd, xd):
            if xd.ndim == 1:
                shape = [1] * yd.ndim
                shape[axis] = xd.shape[0]
                xd = xd.reshape(shape)
            return _cum(yd, jnp.diff(xd, axis=axis))
        return nary(f, [y, ensure_tensor(x)], name="cumulative_trapezoid")
    step = 1.0 if dx is None else dx
    return _unary(lambda yd: _cum(yd, step), y,
                  name="cumulative_trapezoid")


def vander(x, n=None, increasing=False, name=None):
    """Vandermonde matrix (ref: ``tensor/math.py vander``)."""
    return _unary(lambda d: jnp.vander(
        d, N=n, increasing=increasing), x, name="vander")


def multiplex(inputs, index, name=None):
    """Row-wise select across m stacked inputs: ``out[i] =
    inputs[index[i]][i]`` (ref: ``tensor/math.py multiplex :308``).
    TPU design: one stack + one batched gather instead of the reference's
    dedicated CUDA kernel."""
    idx = ensure_tensor(index)
    rows = ensure_tensor(inputs[0]).shape[0] if len(inputs) else 0
    if int(np.prod(idx.shape)) != rows:
        raise ValueError(
            f"multiplex: index must have one entry per row "
            f"({rows}), got shape {idx.shape}")
    if not isinstance(idx._data, jax.core.Tracer):
        # eager: validate up front — XLA gather clamps OOB indices, which
        # would turn a corrupt index tensor into plausible wrong data
        iv = np.asarray(idx._data)
        mx = int(np.max(iv)) if idx.size else 0
        mn = int(np.min(iv)) if idx.size else 0
        if mx >= len(inputs) or mn < 0:
            raise ValueError(
                f"multiplex: index values must be in [0, {len(inputs)}) "
                f"but found {mn if mn < 0 else mx}")

    def f(*ds):
        sel = ds[-1].reshape(-1).astype(jnp.int32)
        stacked = jnp.stack(ds[:-1])          # (m, M, ...)
        rows = jnp.arange(sel.shape[0])
        return stacked[sel, rows]

    return nary(f, list(inputs) + [idx], name="multiplex")


def broadcast_shape(x_shape, y_shape):
    """Broadcast result shape of two shapes (ref: ``tensor/math.py
    broadcast_shape :4189``). Pure host computation — shapes are static
    under XLA."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


floor_mod = mod


# ---- in-place variants (rebind data) --------------------------------------
def _make_inplace(fn):
    from ..autograd import rebind_inplace

    def op(x, *args, **kwargs):
        return rebind_inplace(x, fn(x, *args, **kwargs))
    return op


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
divide_ = _make_inplace(divide)
clip_ = _make_inplace(clip)
scale_ = _make_inplace(scale)
floor_ = _make_inplace(floor)
ceil_ = _make_inplace(ceil)
exp_ = _make_inplace(exp)
sqrt_ = _make_inplace(sqrt)
rsqrt_ = _make_inplace(rsqrt)
reciprocal_ = _make_inplace(reciprocal)
round_ = _make_inplace(round)
sigmoid_ = _make_inplace(sigmoid)
pow_ = _make_inplace(pow)
addmm_ = _make_inplace(addmm)
tanh_ = _make_inplace(tanh)
