"""Quantized serving kernels: w8a16 matmul + int8 pack/unpack helpers.

The low-precision serving subsystem (:mod:`paddle_tpu.serving.quant`)
keeps weights and KV pages in int8 with the scale travelling beside the
tensor; this module owns every raw quant-dtype cast in the tree
(tpu-lint TPU022 forbids ``astype(int8)`` outside ``ops/`` and
``quantization/`` — a bare int8 array with no scale is a bug vector,
not a tensor).

Three layers, matching the house kernel conventions
(:mod:`.fused_kernels` / :mod:`.paged_attention`):

 - **pack/unpack** — :func:`quantize_weight` (per-out-channel symmetric
   absmax, deterministic round-half-away handled by ``jnp.round``),
   :func:`quantize_kv` / :func:`dequantize_kv` (dynamic per-(token,
   head) scales computed in-graph at KV write time — row-independent,
   so the continuous-batching bit-identity contract survives the drop
   to int8).
 - **w8a16_matmul** — activations in 16/32-bit, weights int8, f32 MXU
   accumulation, per-out-channel scale applied in the epilogue (AFTER
   the dot — the AUD006 dequant-placement contract: the int8→wide
   convert feeds exactly one ``dot_general``).  Pallas kernel on TPU,
   canary-probed with a bit-defined XLA mirror fallback so CPU tier-1
   proves the numerics.
 - **autotune** — :func:`tune_w8a16_matmul` routes (block_m, block_n)
   through :mod:`.autotune` ``search`` with a ``KERNEL_SCHEMA`` entry,
   same as the other fused kernels.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_ops import _CompilerParams, _interpret_default, _ceil_to

__all__ = ["quantize_weight", "dequantize_weight", "quantize_kv",
           "dequantize_kv", "w8a16_matmul", "w8a16_matmul_reference",
           "tune_w8a16_matmul", "QMAX"]

# symmetric int8: [-127, 127]; -128 is never produced so negation is
# always exact and the zero-point is identically 0
QMAX = 127.0


# ---------------------------------------------------------------------------
# pack / unpack
# ---------------------------------------------------------------------------
def quantize_weight(w, axis: int = -1):
    """Per-out-channel symmetric int8 quantization of a weight matrix.

    ``axis`` is the OUT-channel axis (kept; absmax reduces over every
    other axis) — for the serve stack's ``(K, N)`` weights that is
    ``axis=1``, giving a ``(N,)`` f32 scale the matmul epilogue applies
    after the dot.  All-zero channels get scale 1 so the divide is
    defined (they quantize to exact zeros either way).

    Returns ``(q_int8, scale_f32)``.  Deterministic: absmax + round is
    a pure function of the weight values.
    """
    w = jnp.asarray(w, jnp.float32)
    axis = axis % w.ndim
    red = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.max(jnp.abs(w), axis=red)
    scale = jnp.where(absmax > 0, absmax, 1.0) / QMAX
    shape = [1] * w.ndim
    shape[axis] = -1
    q = jnp.clip(jnp.round(w / scale.reshape(shape)), -QMAX, QMAX)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_weight(q, scale, axis: int = -1):
    """Inverse of :func:`quantize_weight` (the XLA-mirror epilogue uses
    the fused form instead; this is for tests and calibration reports)."""
    axis = axis % q.ndim
    shape = [1] * q.ndim
    shape[axis] = -1
    return q.astype(jnp.float32) * jnp.asarray(scale).reshape(shape)


def quantize_kv(x):
    """Dynamic int8 quantization over the trailing (head_dim) axis.

    Scales are per-(token, head): ``x`` of shape ``(..., D)`` yields
    int8 values plus a ``(...,)`` f32 scale.  Computed in-graph at KV
    write time — a pure per-row function, so a row's stored bytes never
    depend on its batch neighbours (the decode bit-identity contract).
    """
    x = jnp.asarray(x, jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(absmax > 0, absmax, 1.0) / QMAX
    q = jnp.clip(jnp.round(x / scale[..., None]), -QMAX, QMAX)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_kv(q, scale):
    """Rehydrate int8 KV values with their per-(token, head) scales."""
    return q.astype(jnp.float32) * jnp.asarray(scale)[..., None]


# ---------------------------------------------------------------------------
# w8a16 matmul
# ---------------------------------------------------------------------------
def w8a16_matmul_reference(x, w_q, scale):
    """XLA mirror: widen the int8 weight, f32 dot, scale in the
    epilogue.  This IS the serve-path numerics definition on CPU (the
    canary falls back here), so the order of operations is pinned:
    convert → one dot → per-column scale."""
    acc = jax.lax.dot_general(
        x.astype(jnp.float32), w_q.astype(jnp.float32),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * scale).astype(x.dtype)


def _w8a16_kernel(x_ref, w_ref, s_ref, o_ref):
    acc = jnp.dot(x_ref[...].astype(jnp.float32),
                  w_ref[...].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def _w8a16_pallas(x, w_q, scale, *, block_m, block_n, interpret):
    m, k = x.shape
    n = w_q.shape[1]
    mp, np_ = _ceil_to(m, block_m), _ceil_to(n, block_n)
    xp = jnp.pad(x, ((0, mp - m), (0, 0))) if mp != m else x
    wp = jnp.pad(w_q, ((0, 0), (0, np_ - n))) if np_ != n else w_q
    sp = (jnp.pad(scale, (0, np_ - n)) if np_ != n else scale)[None, :]
    out = pl.pallas_call(
        _w8a16_kernel,
        grid=(mp // block_m, np_ // block_n),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda mi, ni: (mi, 0)),
            pl.BlockSpec((k, block_n), lambda mi, ni: (0, ni)),
            pl.BlockSpec((1, block_n), lambda mi, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda mi, ni: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(xp, wp, sp)
    return out[:m, :n]


_canary_ok = None


def _canary():
    """One-shot probe before trusting the kernel for dispatch — a
    broken lowering degrades to the XLA mirror instead of poisoning
    the serve path (the fused-kernel convention)."""
    global _canary_ok
    if _canary_ok is None:
        try:
            x = jnp.zeros((4, 16), jnp.float32)
            q = jnp.zeros((16, 8), jnp.int8)
            s = jnp.ones((8,), jnp.float32)
            _w8a16_pallas(x, q, s, block_m=8, block_n=128,
                          interpret=_interpret_default())
            _canary_ok = True
        except Exception:
            _canary_ok = False
    return _canary_ok


def w8a16_matmul(x, w_q, scale, *, block_m=None, block_n=None,
                 use_pallas=None, interpret=None):
    """Quantized-weight matmul: ``x @ dequant(w_q, scale)`` computed as
    ``(x @ w_q) * scale`` with f32 accumulation.

    ``x``: ``(..., K)`` float (f32/bf16 — the "a16" half on TPU);
    ``w_q``: ``(K, N)`` int8; ``scale``: ``(N,)`` f32 per-out-channel.
    Output in ``x.dtype``.  Off-TPU the default is the XLA mirror
    (interpret-mode Pallas is a correctness vehicle, not a fast path);
    dispatch decisions are booked on
    ``pt_pallas_calls_total{kernel="w8a16_matmul"}``.
    """
    from .fused_kernels import record_dispatch
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas is None:
        use_pallas = not interpret
    lead = x.shape[:-1]
    if use_pallas and _canary():
        from . import autotune as _at
        x2 = x.reshape(-1, x.shape[-1])
        if block_m is None or block_n is None:
            cached = _at.cache_get("w8a16_matmul",
                                   _tune_key(x2, w_q, interpret)) \
                if _at.enabled() else None
            bm, bn = cached if cached else (8, 128)
            block_m = block_m or int(bm)
            block_n = block_n or int(bn)
        record_dispatch("w8a16_matmul", "pallas")
        out = _w8a16_pallas(x2, w_q, scale, block_m=block_m,
                            block_n=block_n, interpret=interpret)
        return out.reshape(*lead, w_q.shape[1])
    record_dispatch("w8a16_matmul", "fallback")
    return w8a16_matmul_reference(x, w_q, scale)


# ---------------------------------------------------------------------------
# autotune
# ---------------------------------------------------------------------------
def _tune_key(x2, w_q, interpret):
    return (int(x2.shape[0]), int(x2.shape[1]), int(w_q.shape[1]),
            str(x2.dtype), bool(interpret))


def _w8a16_cost_fn(m, k, n, itemsize):
    """Per-candidate cost for the (block_m, block_n) search: int8
    weight tiles + wide activation tiles + the f32 accumulator bound
    the vmem working set; FLOPs/bytes order survivors on the
    roofline."""
    flops = 2.0 * m * k * n
    bytes_ = float(m * k * itemsize + k * n + 4 * n + m * n * itemsize)

    def cost(cfg):
        bm = min(int(cfg[0]), _ceil_to(m, 8))
        bn = min(int(cfg[1]), _ceil_to(n, 128))
        vmem = (bm * k * itemsize        # activation tile
                + k * bn                 # int8 weight tile
                + 4 * bn                 # scale row
                + bm * bn * 4            # f32 accumulator
                + bm * bn * itemsize)    # output tile
        return {"flops": flops, "bytes": bytes_, "vmem_bytes": vmem,
                "mxu_underfill": bm < 8}
    return cost


def tune_w8a16_matmul(x, w_q, scale, *, interpret=None):
    """Warmup autotune for :func:`w8a16_matmul`: generate (block_m,
    block_n) candidates from the shape, prune on the roofline, time the
    survivors on real arrays, cache the winner keyed by (M, K, N,
    dtype) under the ``w8a16_matmul`` schema.  Returns
    ``(best_config, timings)``."""
    from . import autotune as _at
    if interpret is None:
        interpret = _interpret_default()
    x2 = x.reshape(-1, x.shape[-1])
    m, k = x2.shape
    n = w_q.shape[1]
    cost = _w8a16_cost_fn(m, k, n, x.dtype.itemsize)
    cands = _at.generate_candidates(
        [("tile", m, 8), ("tile", n, 128)], cost)

    state = {"x": x2}

    def run(cfg):
        # fresh inputs per call + host readback fence (the tune_mha
        # discipline: identical repeated executions can be cached and
        # block_until_ready no-opped by remote backends)
        out = w8a16_matmul(state["x"], w_q, scale, block_m=int(cfg[0]),
                           block_n=int(cfg[1]), use_pallas=True,
                           interpret=interpret)
        state["x"] = (out[:, :k] * 1e-3).astype(x.dtype) \
            if out.shape[1] >= k else state["x"]
        float(jnp.sum(out.astype(jnp.float32)))

    best, timings = _at.search(
        "w8a16_matmul", _tune_key(x2, w_q, interpret), run, cands,
        cost=cost)
    _at.set_enabled(True)
    return best, timings
