"""Decode-shaped fused attention over a paged KV-cache.

The serving engine (:mod:`paddle_tpu.serving`) keeps each sequence's KV
history in fixed-size *pages* owned by a block pool
(:class:`paddle_tpu.serving.kv_cache.PagePool`); a decode step attends
one new query token per sequence against that sequence's page list.
The reference stack reaches the same shape through
``paddle/fluid/inference`` + external serving engines; here the op is
first-class:

 - :func:`paged_attention_reference` — the XLA path: gather the page
   window ``k_pages[page_tables]`` → masked softmax attention.  Row
   independent by construction, which is what makes continuous
   batching bit-stable (a sequence's logits do not depend on its batch
   neighbours or on which physical pages it landed in).
 - :func:`paged_attention` — dispatcher: Pallas kernel on TPU (canary
   probed once, silent XLA fallback — the :mod:`.fused_kernels`
   convention), reference elsewhere.
 - ``_paged_attention_pallas`` — the kernel: grid ``(batch, pages)``
   with the per-sequence page table scalar-prefetched so each grid
   step's ``BlockSpec`` index map *is* the page-table lookup (the page
   gather never materialises in HBM), online-softmax accumulators in
   VMEM scratch.  Interpret-runnable off-TPU; MXU tiling/tuning on a
   real device is a follow-on (ROADMAP real-TPU evidence round).

Shapes (one layer; the model loops layers):
  q            (B, H, D)        one query token per sequence
  k/v_pages    (P, ps, H, D)    the whole pool, P pages of ps tokens
  page_tables  (B, max_pages)   int32 page ids, position t lives in
                                page ``pt[b, t // ps]`` slot ``t % ps``
  lengths      (B,) int32       valid context per row (pos of the new
                                token + 1; masks padding AND the
                                reserved null page 0 that pads short
                                page tables)
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ops import _CompilerParams, _NEG_INF, _interpret_default

__all__ = ["paged_attention", "paged_attention_reference"]


def paged_attention_reference(q, k_pages, v_pages, page_tables, lengths,
                              *, sm_scale=None):
    """XLA reference: gather the page window, masked softmax attention.

    f32 scores/accumulation regardless of operand dtype (the MXU
    contract from :mod:`.pallas_ops`); output in ``q.dtype``.
    """
    b, h, d = q.shape
    ps = k_pages.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # (B, max_pages, ps, H, D) -> (B, C, H, D); position t sits at
    # context index t because pages fill in order
    k_ctx = k_pages[page_tables].reshape(b, -1, h, d).astype(jnp.float32)
    v_ctx = v_pages[page_tables].reshape(b, -1, h, d).astype(jnp.float32)
    s = jnp.einsum("bhd,bchd->bhc", q.astype(jnp.float32), k_ctx) * sm_scale
    c = k_ctx.shape[1]
    mask = jnp.arange(c, dtype=jnp.int32)[None, :] < lengths[:, None]
    s = jnp.where(mask[:, None, :], s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhc,bchd->bhd", w, v_ctx)
    return o.astype(q.dtype)


def _paged_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, ps, max_pages, sm_scale):
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    length = len_ref[b]

    @pl.when(i * ps < length)
    def _page():
        q = q_ref[...].astype(jnp.float32)          # (H, D)
        k = k_ref[...].astype(jnp.float32)          # (ps, H, D)
        v = v_ref[...].astype(jnp.float32)
        s = jnp.einsum("hd,phd->hp", q, k) * sm_scale
        pos = i * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
        valid = pos < length                         # (1, ps)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # re-mask after the exp: on a fully-dead page m_new stays at
        # _NEG_INF and exp(s - m_new) would be exp(0) = 1 mass
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[...] * alpha \
            + jnp.einsum("hp,phd->hd", p, v)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(i == max_pages - 1)
    def _finalize():
        l = l_scr[...]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, page_tables, lengths,
                            *, sm_scale, interpret):
    b, h, d = q.shape
    ps = k_pages.shape[1]
    max_pages = page_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda bi, i, pt, ln: (bi, 0, 0)),
            pl.BlockSpec((None, ps, h, d),
                         lambda bi, i, pt, ln: (pt[bi, i], 0, 0, 0)),
            pl.BlockSpec((None, ps, h, d),
                         lambda bi, i, pt, ln: (pt[bi, i], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda bi, i, pt, ln: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, ps=ps, max_pages=max_pages,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_tables, lengths, q, k_pages, v_pages)


_canary_ok = None


def _canary():
    """One-shot probe: run the kernel at a toy shape before trusting it
    for dispatch (the SDPA/fused-kernel convention — a broken lowering
    degrades to XLA instead of poisoning the serve path)."""
    global _canary_ok
    if _canary_ok is None:
        try:
            q = jnp.zeros((2, 2, 8), jnp.float32)
            kp = jnp.zeros((3, 4, 2, 8), jnp.float32)
            pt = jnp.zeros((2, 2), jnp.int32)
            ln = jnp.ones((2,), jnp.int32)
            _paged_attention_pallas(q, kp, kp, pt, ln,
                                    sm_scale=1.0,
                                    interpret=_interpret_default())
            _canary_ok = True
        except Exception:
            _canary_ok = False
    return _canary_ok


def paged_attention(q, k_pages, v_pages, page_tables, lengths, *,
                    sm_scale=None, use_pallas=None, interpret=None):
    """Dispatching entry: Pallas paged-attention kernel when eligible,
    XLA gather+softmax reference otherwise.

    Off-TPU the default is the reference (interpret-mode Pallas is a
    correctness vehicle, not a fast path); pass ``use_pallas=True`` to
    force the kernel (tests).  Dispatch decisions are trace-time
    events booked on ``pt_pallas_calls_total{kernel="paged_attention"}``.
    """
    from .fused_kernels import record_dispatch
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas is None:
        use_pallas = not interpret  # on-TPU default; reference on CPU
    if use_pallas and _canary():
        record_dispatch("paged_attention", "pallas")
        return _paged_attention_pallas(q, k_pages, v_pages, page_tables,
                                       lengths, sm_scale=sm_scale,
                                       interpret=interpret)
    record_dispatch("paged_attention", "fallback")
    return paged_attention_reference(q, k_pages, v_pages, page_tables,
                                     lengths, sm_scale=sm_scale)
