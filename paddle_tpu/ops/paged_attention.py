"""Decode-shaped fused attention over a paged KV-cache.

The serving engine (:mod:`paddle_tpu.serving`) keeps each sequence's KV
history in fixed-size *pages* owned by a block pool
(:class:`paddle_tpu.serving.kv_cache.PagePool`); a decode step attends
one new query token per sequence against that sequence's page list.
The reference stack reaches the same shape through
``paddle/fluid/inference`` + external serving engines; here the op is
first-class:

 - :func:`paged_attention_reference` — the XLA path: gather the page
   window ``k_pages[page_tables]`` → masked softmax attention.  Row
   independent by construction, which is what makes continuous
   batching bit-stable (a sequence's logits do not depend on its batch
   neighbours or on which physical pages it landed in).
 - :func:`paged_attention` — dispatcher: Pallas kernel on TPU (canary
   probed once, silent XLA fallback — the :mod:`.fused_kernels`
   convention), reference elsewhere.
 - ``_paged_attention_pallas`` — the kernel: grid ``(batch, pages)``
   with the per-sequence page table scalar-prefetched so each grid
   step's ``BlockSpec`` index map *is* the page-table lookup (the page
   gather never materialises in HBM), online-softmax accumulators in
   VMEM scratch.  Interpret-runnable off-TPU; MXU tiling/tuning on a
   real device is a follow-on (ROADMAP real-TPU evidence round).

Shapes (one layer; the model loops layers):
  q            (B, H, D)        one query token per sequence
  k/v_pages    (P, ps, H, D)    the whole pool, P pages of ps tokens
  page_tables  (B, max_pages)   int32 page ids, position t lives in
                                page ``pt[b, t // ps]`` slot ``t % ps``
  lengths      (B,) int32       valid context per row (pos of the new
                                token + 1; masks padding AND the
                                reserved null page 0 that pads short
                                page tables)
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ops import _CompilerParams, _NEG_INF, _interpret_default

__all__ = ["paged_attention", "paged_attention_reference",
           "paged_attention_int8", "paged_attention_int8_reference",
           "tune_paged_attention_int8"]


def paged_attention_reference(q, k_pages, v_pages, page_tables, lengths,
                              *, sm_scale=None):
    """XLA reference: gather the page window, masked softmax attention.

    f32 scores/accumulation regardless of operand dtype (the MXU
    contract from :mod:`.pallas_ops`); output in ``q.dtype``.
    """
    b, h, d = q.shape
    ps = k_pages.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # (B, max_pages, ps, H, D) -> (B, C, H, D); position t sits at
    # context index t because pages fill in order
    k_ctx = k_pages[page_tables].reshape(b, -1, h, d)
    v_ctx = v_pages[page_tables].reshape(b, -1, h, d)
    s = jnp.einsum("bhd,bchd->bhc", q, k_ctx,
                   preferred_element_type=jnp.float32) * sm_scale
    c = k_ctx.shape[1]
    mask = jnp.arange(c, dtype=jnp.int32)[None, :] < lengths[:, None]
    s = jnp.where(mask[:, None, :], s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhc,bchd->bhd", w.astype(v_ctx.dtype), v_ctx,
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


def _paged_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, ps, max_pages, sm_scale):
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    length = len_ref[b]

    @pl.when(i * ps < length)
    def _page():
        q = q_ref[...].astype(jnp.float32)          # (H, D)
        k = k_ref[...].astype(jnp.float32)          # (ps, H, D)
        v = v_ref[...].astype(jnp.float32)
        s = jnp.einsum("hd,phd->hp", q, k) * sm_scale
        pos = i * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
        valid = pos < length                         # (1, ps)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # re-mask after the exp: on a fully-dead page m_new stays at
        # _NEG_INF and exp(s - m_new) would be exp(0) = 1 mass
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[...] * alpha \
            + jnp.einsum("hp,phd->hd", p, v)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(i == max_pages - 1)
    def _finalize():
        l = l_scr[...]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, page_tables, lengths,
                            *, sm_scale, interpret):
    b, h, d = q.shape
    ps = k_pages.shape[1]
    max_pages = page_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda bi, i, pt, ln: (bi, 0, 0)),
            pl.BlockSpec((None, ps, h, d),
                         lambda bi, i, pt, ln: (pt[bi, i], 0, 0, 0)),
            pl.BlockSpec((None, ps, h, d),
                         lambda bi, i, pt, ln: (pt[bi, i], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda bi, i, pt, ln: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, ps=ps, max_pages=max_pages,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_tables, lengths, q, k_pages, v_pages)


_canary_ok = None


def _canary():
    """One-shot probe: run the kernel at a toy shape before trusting it
    for dispatch (the SDPA/fused-kernel convention — a broken lowering
    degrades to XLA instead of poisoning the serve path)."""
    global _canary_ok
    if _canary_ok is None:
        try:
            q = jnp.zeros((2, 2, 8), jnp.float32)
            kp = jnp.zeros((3, 4, 2, 8), jnp.float32)
            pt = jnp.zeros((2, 2), jnp.int32)
            ln = jnp.ones((2,), jnp.int32)
            _paged_attention_pallas(q, kp, kp, pt, ln,
                                    sm_scale=1.0,
                                    interpret=_interpret_default())
            _canary_ok = True
        except Exception:
            _canary_ok = False
    return _canary_ok


def paged_attention(q, k_pages, v_pages, page_tables, lengths, *,
                    sm_scale=None, use_pallas=None, interpret=None):
    """Dispatching entry: Pallas paged-attention kernel when eligible,
    XLA gather+softmax reference otherwise.

    Off-TPU the default is the reference (interpret-mode Pallas is a
    correctness vehicle, not a fast path); pass ``use_pallas=True`` to
    force the kernel (tests).  Dispatch decisions are trace-time
    events booked on ``pt_pallas_calls_total{kernel="paged_attention"}``.
    """
    from .fused_kernels import record_dispatch
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas is None:
        use_pallas = not interpret  # on-TPU default; reference on CPU
    if use_pallas and _canary():
        record_dispatch("paged_attention", "pallas")
        return _paged_attention_pallas(q, k_pages, v_pages, page_tables,
                                       lengths, sm_scale=sm_scale,
                                       interpret=interpret)
    record_dispatch("paged_attention", "fallback")
    return paged_attention_reference(q, k_pages, v_pages, page_tables,
                                     lengths, sm_scale=sm_scale)


# ---------------------------------------------------------------------------
# int8-KV variant (the low-precision serving subsystem)
# ---------------------------------------------------------------------------
# Same attention, but the pool stores int8 values with per-(token, head)
# f32 scales riding beside them (``PagePool(dtype=int8, scale_pages=
# True)``): k/v_pages are (P, ps, H, D) int8 and k/v_scale are
# (P, ps, H) f32.  Dequantization happens at the attention's edge —
# scores and accumulation stay f32, so the math after the unpack is the
# exact fp32 kernel above and the row-independence (bit-identity)
# argument carries over unchanged.

def paged_attention_int8_reference(q, k_pages, v_pages, k_scale, v_scale,
                                   page_tables, lengths, *, sm_scale=None):
    """XLA reference for int8 pages: gather values AND scales through
    the page table, dequantize, masked softmax attention (f32)."""
    b, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    k_ctx = (k_pages[page_tables].astype(jnp.float32)
             * k_scale[page_tables][..., None]).reshape(b, -1, h, d)
    v_ctx = (v_pages[page_tables].astype(jnp.float32)
             * v_scale[page_tables][..., None]).reshape(b, -1, h, d)
    s = jnp.einsum("bhd,bchd->bhc", q.astype(jnp.float32), k_ctx) * sm_scale
    c = k_ctx.shape[1]
    mask = jnp.arange(c, dtype=jnp.int32)[None, :] < lengths[:, None]
    s = jnp.where(mask[:, None, :], s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhc,bchd->bhd", w, v_ctx)
    return o.astype(q.dtype)


def _paged_kernel_int8(pt_ref, len_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, o_ref, m_scr, l_scr, acc_scr, *, ps,
                       max_pages, sm_scale):
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    length = len_ref[b]

    @pl.when(i * ps < length)
    def _page():
        q = q_ref[...].astype(jnp.float32)          # (H, D)
        # unpack at the edge: int8 page * per-(token, head) scale
        k = k_ref[...].astype(jnp.float32) * ks_ref[...][..., None]
        v = v_ref[...].astype(jnp.float32) * vs_ref[...][..., None]
        s = jnp.einsum("hd,phd->hp", q, k) * sm_scale
        pos = i * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
        valid = pos < length                         # (1, ps)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # re-mask after the exp (see _paged_kernel)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[...] * alpha \
            + jnp.einsum("hp,phd->hd", p, v)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(i == max_pages - 1)
    def _finalize():
        l = l_scr[...]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)


def _paged_attention_int8_pallas(q, k_pages, v_pages, k_scale, v_scale,
                                 page_tables, lengths, *, sm_scale,
                                 interpret, batch_semantics="parallel"):
    b, h, d = q.shape
    ps = k_pages.shape[1]
    max_pages = page_tables.shape[1]
    page_spec = pl.BlockSpec((None, ps, h, d),
                             lambda bi, i, pt, ln: (pt[bi, i], 0, 0, 0))
    scale_spec = pl.BlockSpec((None, ps, h),
                              lambda bi, i, pt, ln: (pt[bi, i], 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda bi, i, pt, ln: (bi, 0, 0)),
            page_spec, page_spec, scale_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda bi, i, pt, ln: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel_int8, ps=ps,
                               max_pages=max_pages, sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=(batch_semantics, "arbitrary")),
        interpret=interpret,
    )(page_tables, lengths, q, k_pages, v_pages, k_scale, v_scale)


_canary_int8_ok = None


def _canary_int8():
    global _canary_int8_ok
    if _canary_int8_ok is None:
        try:
            q = jnp.zeros((2, 2, 8), jnp.float32)
            kp = jnp.zeros((3, 4, 2, 8), jnp.int8)
            ks = jnp.ones((3, 4, 2), jnp.float32)
            pt = jnp.zeros((2, 2), jnp.int32)
            ln = jnp.ones((2,), jnp.int32)
            _paged_attention_int8_pallas(q, kp, kp, ks, ks, pt, ln,
                                         sm_scale=1.0,
                                         interpret=_interpret_default())
            _canary_int8_ok = True
        except Exception:
            _canary_int8_ok = False
    return _canary_int8_ok


def paged_attention_int8(q, k_pages, v_pages, k_scale, v_scale,
                         page_tables, lengths, *, sm_scale=None,
                         use_pallas=None, interpret=None):
    """Dispatching entry for the int8-KV pool: Pallas kernel when
    eligible (canary-probed), XLA gather+dequant+softmax reference
    otherwise — booked on
    ``pt_pallas_calls_total{kernel="paged_attention_int8"}``."""
    from .fused_kernels import record_dispatch
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    if use_pallas is None:
        use_pallas = not interpret  # on-TPU default; reference on CPU
    if use_pallas and _canary_int8():
        from . import autotune as _at
        sem = "parallel"
        if _at.enabled():
            cached = _at.cache_get("paged_attention_int8",
                                   _int8_tune_key(q, k_pages, interpret))
            if cached:
                sem = str(cached[0])
        record_dispatch("paged_attention_int8", "pallas")
        return _paged_attention_int8_pallas(
            q, k_pages, v_pages, k_scale, v_scale, page_tables, lengths,
            sm_scale=sm_scale, interpret=interpret, batch_semantics=sem)
    record_dispatch("paged_attention_int8", "fallback")
    return paged_attention_int8_reference(
        q, k_pages, v_pages, k_scale, v_scale, page_tables, lengths,
        sm_scale=sm_scale)


def _int8_tune_key(q, k_pages, interpret):
    b, h, d = q.shape
    return (b, h, d, int(k_pages.shape[0]), int(k_pages.shape[1]),
            int(interpret))


def tune_paged_attention_int8(q, k_pages, v_pages, k_scale, v_scale,
                              page_tables, lengths, *, interpret=None):
    """Warmup autotune over the kernel's grid-semantics choice (the
    batch axis can run parallel or arbitrary; which wins depends on the
    page count per core) via :func:`autotune.search` under the
    ``paged_attention_int8`` schema."""
    from . import autotune as _at
    if interpret is None:
        interpret = _interpret_default()
    b, h, d = q.shape
    ps = k_pages.shape[1]
    # int8 k/v page tiles + f32 scales + online-softmax scratch per step
    vmem = 2 * ps * h * d + 2 * ps * h * 4 + h * d * 4 + 2 * h * 4

    def cost(cfg):
        return {"flops": 4.0 * b * h * ps * d * page_tables.shape[1],
                "bytes": float(q.size * 4 + 2 * k_pages.size
                               + 2 * k_scale.size * 4),
                "vmem_bytes": vmem, "mxu_underfill": False}

    cands = _at.generate_candidates(
        [("choice", ("parallel", "arbitrary"))], cost)

    def run(cfg):
        out = _paged_attention_int8_pallas(
            q, k_pages, v_pages, k_scale, v_scale, page_tables, lengths,
            sm_scale=1.0 / math.sqrt(d), interpret=interpret,
            batch_semantics=str(cfg[0]))
        float(jnp.sum(out.astype(jnp.float32)))

    best, timings = _at.search(
        "paged_attention_int8", _int8_tune_key(q, k_pages, interpret),
        run, cands, cost=cost)
    _at.set_enabled(True)
    return best, timings
