"""Kernel autotune cache (ref: ``paddle/phi/kernels/autotune/`` —
``cache.h`` AutoTuneCache, ``auto_tune_base.h`` timing loop, enabled via
``paddle.incubate.autotune.set_config``).

TPU-native scope: XLA already autotunes its own kernels; what remains is
the choice of PALLAS kernel launch configs (flash-attention block sizes).
Because Pallas calls usually execute inside a jit trace (where nothing
can be timed), tuning is a WARMUP step: time candidates eagerly once per
(shape, dtype, flags) key, cache the winner, and let traced calls read
the cache. The cache persists to JSON like the reference's autotune
cache file.
"""
from __future__ import annotations

import json
import time

__all__ = ["enabled", "set_enabled", "cache_get", "cache_put",
           "cache_clear", "save_cache", "load_cache", "time_candidates"]

_enabled = False
_cache: dict = {}


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool):
    global _enabled
    _enabled = bool(flag)


def _key(kernel: str, key: tuple) -> str:
    return json.dumps([kernel, list(key)])


def cache_get(kernel: str, key: tuple):
    hit = _cache.get(_key(kernel, key))
    return tuple(hit) if hit is not None else None


def cache_put(kernel: str, key: tuple, config):
    _cache[_key(kernel, key)] = list(config)


def cache_clear():
    _cache.clear()


def save_cache(path: str):
    with open(path, "w") as f:
        json.dump(_cache, f)


def load_cache(path: str):
    with open(path) as f:
        _cache.update(json.load(f))


def time_candidates(run, candidates, warmup=1, iters=3):
    """Pick the fastest config: ``run(config)`` must execute the kernel
    and block until ready (ref ``auto_tune_base.h`` RunAndMeasureKernel).
    Returns (best_config, {config: seconds}). Configs that fail to
    compile/run are skipped."""
    timings = {}
    best, best_t = None, float("inf")
    for cfg in candidates:
        try:
            for _ in range(warmup):
                run(cfg)
            t0 = time.perf_counter()
            for _ in range(iters):
                run(cfg)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue
        timings[cfg] = dt
        if dt < best_t:
            best, best_t = cfg, dt
    if best is None:
        raise RuntimeError("no autotune candidate ran successfully")
    return best, timings
