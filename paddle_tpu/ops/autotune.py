"""Search-based kernel autotuner (ref: ``paddle/phi/kernels/autotune/``
— ``cache.h`` AutoTuneCache, ``auto_tune_base.h`` timing loop, enabled
via ``paddle.incubate.autotune.set_config``).

TPU-native scope: XLA already autotunes its own kernels; what remains is
the choice of PALLAS kernel launch configs (flash-attention block sizes,
fused layernorm row tiles + grid semantics, fused softmax-xent tiles).
Because Pallas calls usually execute inside a jit trace (where nothing
can be timed), tuning is a WARMUP step: :func:`search` runs once per
(shape, dtype, flags) key — candidates are first pruned by a
``cost_model/`` seed (analytic FLOPs/bytes → roofline ordering; configs
whose tiles overflow vmem or underfill the MXU are rejected before any
timing), the survivors are timed eagerly, and the winner is cached for
traced calls to read.

Cache keys include the device kind, jax version, and a per-kernel
schema version, so a cache tuned in CPU interpret mode is never served
to a real TPU run (or to a kernel whose meaning of "config" changed).
The cache persists to JSON (``save_cache``/``load_cache``, or
automatically via the ``PT_AUTOTUNE_CACHE`` env var) and stale entries
are dropped on load rather than crashing.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["enabled", "set_enabled", "cache_get", "cache_put",
           "cache_clear", "save_cache", "load_cache", "time_candidates",
           "search", "prune_candidates", "roofline_seconds",
           "analytic_seed", "generate_candidates", "bump_schema",
           "summary", "KERNEL_SCHEMA", "VMEM_LIMIT_BYTES"]

_enabled = False
_cache: dict = {}
_autoloaded = False
_searches: dict = {}          # kernel -> last search stats (bench block)

# Config schema version per kernel: bump when the meaning of a cached
# config tuple changes (e.g. flash_mha grew tuner-owned clamping in v2;
# ln/xent moved from static candidate tables to generated spaces in v2
# so PR 8-era winners can't be served to the generator-backed search).
KERNEL_SCHEMA = {
    "flash_mha": 2,
    "fused_layer_norm": 2,
    "fused_softmax_xent": 2,
    "fused_ln_matmul": 1,
    "fused_matmul_bias_gelu": 1,
    "w8a16_matmul": 1,
    "paged_attention_int8": 1,
}


def bump_schema(kernel: str) -> int:
    """Bump (register-if-new) a kernel's config schema version.

    The schema version is part of every cache key, so bumping it makes
    previously persisted winners invisible to :func:`cache_get` and
    dropped by :func:`load_cache` — the next :func:`search` re-times and
    re-persists under the new version instead of serving a config whose
    meaning changed. Returns the new version."""
    KERNEL_SCHEMA[kernel] = KERNEL_SCHEMA.get(kernel, 1) + 1
    return KERNEL_SCHEMA[kernel]

# Roofline constants: v4-class core (~275 TFLOP/s bf16 MXU, ~1.2 TB/s
# HBM). Only the RATIO matters — the roofline orders candidates, the
# timing loop decides.
PEAK_FLOPS = 275e12
HBM_BW = 1.2e12
# ~16 MB vmem/core, minus headroom for Mosaic's own buffers.
VMEM_LIMIT_BYTES = 12 * 1024 * 1024

_ENV_CACHE_VAR = "PT_AUTOTUNE_CACHE"


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool):
    global _enabled
    _enabled = bool(flag)


# ---------------------------------------------------------------------------
# cache keys + persistence
# ---------------------------------------------------------------------------
def _env_fingerprint():
    """(device_kind, jax_version) of the process — part of every cache
    key so interpret-mode CPU tunings never leak onto real TPUs."""
    import jax
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    return str(kind), str(jax.__version__)


def _key(kernel: str, key: tuple) -> str:
    kind, ver = _env_fingerprint()
    return json.dumps(
        [kernel, KERNEL_SCHEMA.get(kernel, 1), kind, ver, list(key)])


def _autoload():
    """Lazily pull the persisted cache named by PT_AUTOTUNE_CACHE (if
    any) the first time the cache is consulted, so a second process
    reloads winners without re-searching."""
    global _autoloaded
    if _autoloaded:
        return
    _autoloaded = True
    path = os.environ.get(_ENV_CACHE_VAR)
    if path and os.path.exists(path):
        try:
            load_cache(path)
        except Exception:
            pass


def cache_get(kernel: str, key: tuple):
    _autoload()
    hit = _cache.get(_key(kernel, key))
    return tuple(hit) if hit is not None else None


def cache_put(kernel: str, key: tuple, config):
    _cache[_key(kernel, key)] = list(config)


def cache_clear():
    _cache.clear()
    _searches.clear()


def save_cache(path: str):
    with open(path, "w") as f:
        json.dump(_cache, f)


def load_cache(path: str):
    """Merge a persisted cache, dropping entries whose device kind, jax
    version, or kernel schema no longer match this process (stale keys
    are invalidated, not an error)."""
    with open(path) as f:
        raw = json.load(f)
    kind, ver = _env_fingerprint()
    for k, v in raw.items():
        try:
            kernel, schema, k_kind, k_ver, _ = json.loads(k)
        except Exception:
            continue                      # pre-schema or corrupt entry
        if (k_kind, k_ver) != (kind, ver):
            continue
        if schema != KERNEL_SCHEMA.get(kernel, 1):
            continue
        _cache[k] = v


# ---------------------------------------------------------------------------
# cost-model seed + pruning
# ---------------------------------------------------------------------------
def analytic_seed(fn, *example_args):
    """Seed a kernel's cost function from ``cost_model``: XLA's analytic
    FLOPs/bytes for the pure-jnp reference of the fused cluster. Returns
    ``{"flops", "bytes"}`` or None when the analysis is unavailable (the
    caller falls back to its closed-form estimate)."""
    try:
        from ..cost_model.cost_model import CostModel
        c = CostModel.analytic_cost(fn, *example_args)
        flops = float(c.get("flops", 0.0))
        bytes_ = float(c.get("bytes accessed", c.get("bytes", 0.0)))
        if flops <= 0.0 and bytes_ <= 0.0:
            return None
        return {"flops": flops, "bytes": bytes_}
    except Exception:
        return None


def roofline_seconds(flops: float, bytes_: float) -> float:
    """Roofline time estimate: the kernel is bound by whichever of MXU
    throughput or HBM bandwidth it saturates first."""
    return max(float(flops) / PEAK_FLOPS, float(bytes_) / HBM_BW)


def prune_candidates(candidates, cost, vmem_limit=None):
    """Filter a candidate list through a per-config cost estimate before
    any timing. ``cost(cfg)`` returns a dict with ``vmem_bytes`` (tile
    working set), ``mxu_underfill`` (tiles below the native compute tile
    → rejected), and ``flops``/``bytes`` feeding the roofline ordering;
    returning None rejects the config outright.

    Returns (survivors_sorted_best_first, pruned_configs)."""
    if vmem_limit is None:
        vmem_limit = VMEM_LIMIT_BYTES
    scored, pruned = [], []
    for cfg in candidates:
        try:
            c = cost(cfg)
        except Exception:
            c = None
        if c is None:
            pruned.append(cfg)
            continue
        if float(c.get("vmem_bytes", 0.0)) > vmem_limit:
            pruned.append(cfg)
            continue
        if c.get("mxu_underfill", False):
            pruned.append(cfg)
            continue
        scored.append((roofline_seconds(c.get("flops", 0.0),
                                        c.get("bytes", 0.0)), cfg))
    scored.sort(key=lambda sc: sc[0])
    return [cfg for _, cfg in scored], pruned


def _tile_options(total: int, align: int):
    """Aligned power-of-two tile sizes up to (and including) ``total``
    rounded up to ``align`` — the hardware-shaped axis walk every
    generated candidate space is built from."""
    cap = max(align, ((int(total) + align - 1) // align) * align)
    out, t = [], align
    while t < cap:
        out.append(t)
        t *= 2
    out.append(cap)
    return sorted(set(out))


def generate_candidates(axes, cost, vmem_limit=None, max_candidates=10):
    """Cost-model-guided candidate *generation* (vs the PR 8 static
    tables): emit launch-config tuples for a fused cluster from its
    shape, prune them through ``cost`` exactly like :func:`search`
    does (vmem overflow / MXU underfill rejected, survivors roofline-
    ordered), and keep the ``max_candidates`` best for timing.

    ``axes`` describes one config-tuple position each, in order:

    - ``("tile", total, align)`` — aligned pow-2 tile sizes covering
      ``total`` (clamped to its padded extent),
    - ``("choice", (a, b, ...))`` — an enumerated option (e.g. the
      parallel/arbitrary grid-semantics bit).

    Returns the survivors best-roofline-first; raises when the cost
    model prunes every generated config (same contract as search)."""
    import itertools
    options = []
    for ax in axes:
        kind = ax[0]
        if kind == "tile":
            _, total, align = ax
            options.append(_tile_options(total, align))
        elif kind == "choice":
            options.append(list(ax[1]))
        else:
            raise ValueError(f"unknown candidate axis kind {kind!r}")
    cands = [tuple(c) for c in itertools.product(*options)]
    survivors, pruned = prune_candidates(cands, cost, vmem_limit)
    if not survivors:
        raise RuntimeError(
            f"autotune: candidate generator pruned every config "
            f"({len(pruned)} generated and rejected)")
    return survivors[:max_candidates]


# ---------------------------------------------------------------------------
# timing + search
# ---------------------------------------------------------------------------
def time_candidates(run, candidates, warmup=1, iters=3):
    """Pick the fastest config: ``run(config)`` must execute the kernel
    and block until ready (ref ``auto_tune_base.h`` RunAndMeasureKernel).
    Returns (best_config, {config: seconds}). Configs that fail to
    compile/run are skipped."""
    timings = {}
    best, best_t = None, float("inf")
    for cfg in candidates:
        try:
            for _ in range(warmup):
                run(cfg)
            t0 = time.perf_counter()
            for _ in range(iters):
                run(cfg)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue
        timings[cfg] = dt
        if dt < best_t:
            best, best_t = cfg, dt
    if best is None:
        raise RuntimeError("no autotune candidate ran successfully")
    return best, timings


def _metrics():
    try:
        from ..observability.metrics import get_registry
        from ..observability.telemetry import get_telemetry
        if not get_telemetry().enabled:
            return None, None, None
        reg = get_registry()
        return (reg.counter("pt_autotune_cache_hits_total",
                            "Autotune searches answered from cache",
                            labelnames=("kernel",)),
                reg.counter("pt_autotune_cache_misses_total",
                            "Autotune searches that had to time candidates",
                            labelnames=("kernel",)),
                reg.counter("pt_autotune_search_seconds",
                            "Wall seconds spent timing autotune candidates",
                            labelnames=("kernel",)))
    except Exception:
        return None, None, None


def search(kernel: str, key: tuple, run, candidates, cost=None,
           vmem_limit=None, warmup=1, iters=3):
    """The tuner's front door: cache hit → return the cached winner
    without running anything; miss → prune ``candidates`` through
    ``cost`` (see :func:`prune_candidates`), time the survivors with
    ``run``, cache + (if ``PT_AUTOTUNE_CACHE`` is set) persist the
    winner. Returns (best_config, {config: seconds}) — timings empty on
    a cache hit."""
    hits, misses, secs = _metrics()
    cached = cache_get(kernel, key)
    if cached is not None:
        if hits is not None:
            hits.inc(kernel=kernel)
        return cached, {}
    if misses is not None:
        misses.inc(kernel=kernel)

    candidates = list(candidates)
    if cost is not None:
        survivors, pruned = prune_candidates(candidates, cost, vmem_limit)
    else:
        survivors, pruned = candidates, []
    if not survivors:
        raise RuntimeError(
            f"autotune[{kernel}]: cost model pruned every candidate "
            f"({len(pruned)} rejected)")

    t0 = time.perf_counter()
    best, timings = time_candidates(run, survivors, warmup=warmup,
                                    iters=iters)
    elapsed = time.perf_counter() - t0
    if secs is not None:
        secs.inc(elapsed, kernel=kernel)

    cache_put(kernel, key, best)
    _searches[kernel] = {
        "key": list(key),
        "best": list(best),
        "search_seconds": elapsed,
        "timed": len(timings),
        "pruned": len(pruned),
    }
    path = os.environ.get(_ENV_CACHE_VAR)
    if path:
        try:
            save_cache(path)
        except Exception:
            pass
    return tuple(best), timings


def summary():
    """Per-kernel stats of the searches this process ran (winning
    config, search seconds, timed/pruned counts) — attached to bench
    records as the ``autotune`` block."""
    return {k: dict(v) for k, v in _searches.items()}
