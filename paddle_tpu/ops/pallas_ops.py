"""Hand-written TPU Pallas kernels — the `phi/kernels/fusion` equivalent.

The reference ships fused CUDA kernels (flash attention:
``paddle/phi/kernels/gpu/flash_attn_kernel.cu`` + vendored
``third_party/flashattn``; fused rope/adam under
``paddle/phi/kernels/fusion/``).  On TPU the only ops worth hand-writing
are the ones XLA cannot fuse into O(S) memory itself — attention.  This
module implements FlashAttention-2 style tiled attention (forward +
backward as ``jax.custom_vjp``) with online softmax, f32 accumulation,
and MXU-aligned 128x128 tiles.

Everything here works on raw ``jnp`` arrays in **(B, H, S, D)** layout;
`flash_attention` adapts from the paddle **(B, S, H, D)** convention and
from the framework `Tensor` type.  On non-TPU backends the kernels run
in Pallas interpret mode so the exact same code path is testable on the
CPU mesh used by the test-suite.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "mha", "mha_reference"]

_NEG_INF = -1e30
_LANES = 128


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _interpret_default() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, causal, sm_scale, block_q, block_k, q_len, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale

        kcol = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kcol < kv_len
        if causal:
            qrow = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kcol <= qrow + (kv_len - q_len))
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v_ref[0].astype(jnp.float32),
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Blocks fully above the diagonal have nothing to attend to.
        @pl.when(qi * block_q + block_q - 1 + (kv_len - q_len)
                 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, :] = m_scr[:, 0] + jnp.log(l_safe[:, 0])


def _fwd(q, k, v, *, causal, sm_scale, block_q, block_k, q_len, kv_len,
         interpret):
    bh, sq, d = q.shape
    skv = k.shape[1]
    nq, nk = sq // block_q, skv // block_k
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, q_len=q_len, kv_len=kv_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, causal, sm_scale, block_q, block_k,
                   q_len, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        kcol = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kcol < kv_len
        if causal:
            qrow = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kcol <= qrow + (kv_len - q_len))
        p = jnp.exp(s - lse_ref[0, :][:, None])
        p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :][:, None])
        dq_scr[:] = dq_scr[:] + sm_scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 + (kv_len - q_len)
                 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, causal, sm_scale,
                    block_q, block_k, q_len, kv_len):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        kcol = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kcol < kv_len
        if causal:
            qrow = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, kcol <= qrow + (kv_len - q_len))
        p = jnp.exp(s - lse_ref[0, :][:, None])
        p = jnp.where(mask, p, 0.0)
        # dv += p^T @ do
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :][:, None])
        # dk += ds^T @ q
        dk_scr[:] = dk_scr[:] + sm_scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 + (kv_len - q_len)
                 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, *, causal, sm_scale, block_q, block_k,
         q_len, kv_len, interpret):
    bh, sq, d = q.shape
    skv = k.shape[1]
    nq, nk = sq // block_q, skv // block_k
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)  # (bh, sq)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, q_len=q_len,
                          kv_len=kv_len),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, q_len=q_len,
                          kv_len=kv_len),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, skv, d), k.dtype),
            jax.ShapeDtypeStruct((bh, skv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper on padded (BH, S, D) arrays
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, q_len, kv_len,
           interpret):
    out, _ = _fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                  block_q=block_q, block_k=block_k, q_len=q_len,
                  kv_len=kv_len, interpret=interpret)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, q_len, kv_len,
               interpret):
    out, lse = _fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                    block_q=block_q, block_k=block_k, q_len=q_len,
                    kv_len=kv_len, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_len, kv_len, interpret,
               res, do):
    q, k, v, out, lse = res
    return _bwd(q, k, v, out, lse, do, causal=causal, sm_scale=sm_scale,
                block_q=block_q, block_k=block_k, q_len=q_len,
                kv_len=kv_len, interpret=interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
def _mha_tune_key(q, k, causal, interpret):
    return (q.shape[2], k.shape[2], q.shape[3], str(q.dtype), bool(causal),
            bool(interpret))


def mha(q, k, v, *, causal=False, sm_scale=None, block_q=None, block_k=None,
        interpret=None):
    """Tiled flash attention on raw arrays in (B, H, S, D) layout.

    Pads S to the tile size and D to the 128-lane width (zero-padding is
    exact: padded head dims contribute 0 to logits; padded keys are
    masked by ``kv_len``; padded query rows are sliced off).

    ``block_q``/``block_k`` default to an autotuned choice when
    :func:`tune_mha` has cached one for this (seq, d, dtype, causal) key
    (ref ``paddle/phi/kernels/autotune/``), else 128/128.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, h, sq, d = q.shape
    skv = k.shape[2]
    if block_q is None and block_k is None:
        from . import autotune as _at
        hit = _at.cache_get("flash_mha", _mha_tune_key(
            q, k, causal, interpret)) if _at.enabled() else None
        if hit is not None:
            block_q, block_k = hit
    # explicitly passed blocks always win; unset ones default to 128
    block_q = 128 if block_q is None else block_q
    block_k = 128 if block_k is None else block_k
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, _ceil_to(sq, 8))
    block_k = min(block_k, _ceil_to(skv, 8))
    sq_p, skv_p = _ceil_to(sq, block_q), _ceil_to(skv, block_k)
    d_p = _ceil_to(d, _LANES)

    def prep(x, s_p):
        x = x.reshape(b * h, x.shape[2], d)
        return jnp.pad(x, ((0, 0), (0, s_p - x.shape[1]), (0, d_p - d)))

    qp, kp, vp = prep(q, sq_p), prep(k, skv_p), prep(v, skv_p)
    out = _flash(qp, kp, vp, causal, sm_scale, block_q, block_k, sq, skv,
                 interpret)
    return out[:, :sq, :d].reshape(b, h, sq, d)


def tune_mha(q, k, v, *, causal=False, interpret=None,
             candidates=((128, 128), (256, 128), (128, 256), (256, 256),
                         (512, 128))):
    """Warmup autotune for :func:`mha`: eagerly time the candidate
    (block_q, block_k) configs on REAL arrays, cache the winner keyed by
    (seq, d, dtype, causal) so subsequent (including traced) calls pick
    it up. Returns (best_config, timings). Candidates larger than the
    padded sequence are deduplicated after clamping."""
    import jax as _jax
    from . import autotune as _at

    if interpret is None:
        interpret = _interpret_default()
    sq, skv = q.shape[2], k.shape[2]
    seen, todo = set(), []
    for bq, bk in candidates:
        clamped = (min(bq, _ceil_to(sq, 8)), min(bk, _ceil_to(skv, 8)))
        if clamped not in seen:
            seen.add(clamped)
            todo.append(clamped)

    def run(cfg):
        out = mha(q, k, v, causal=causal, block_q=cfg[0], block_k=cfg[1],
                  interpret=interpret)
        _jax.block_until_ready(out)

    best, timings = _at.time_candidates(run, todo)
    _at.cache_put("flash_mha", _mha_tune_key(q, k, causal, interpret), best)
    # explicit tuning is intent: turn cache consumption on (still
    # switch-offable via incubate.autotune.set_config kernel.enable=False)
    _at.set_enabled(True)
    return best, timings


def mha_reference(q, k, v, *, causal=False, sm_scale=None):
    """Plain-XLA reference used by the kernel unit tests ((B,H,S,D))."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, skv = s.shape[-2], s.shape[-1]
        qrow = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 0)
        kcol = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 1)
        s = jnp.where(kcol <= qrow + (skv - sq), s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(
        q.dtype)


def flash_attention(query, key, value, *, causal=False, interpret=None):
    """Framework-facing entry: paddle (B, S, H, D) layout, Tensor in/out.

    TPU replacement for the reference's flash_attn path
    (``python/paddle/nn/functional/flash_attention.py`` →
    ``paddle/phi/kernels/gpu/flash_attn_kernel.cu``).
    """
    from .op_utils import ensure_tensor, nary

    q, k, v = (ensure_tensor(t) for t in (query, key, value))

    def f(qd, kd, vd):
        o = mha(jnp.swapaxes(qd, 1, 2), jnp.swapaxes(kd, 1, 2),
                jnp.swapaxes(vd, 1, 2), causal=causal, interpret=interpret)
        return jnp.swapaxes(o, 1, 2)

    return nary(f, [q, k, v], name="flash_attention")
