"""Hand-written TPU Pallas kernels — the `phi/kernels/fusion` equivalent.

The reference ships fused CUDA kernels (flash attention:
``paddle/phi/kernels/gpu/flash_attn_kernel.cu`` + vendored
``third_party/flashattn``; fused rope/adam under
``paddle/phi/kernels/fusion/``).  On TPU the only ops worth hand-writing
are the ones XLA cannot fuse into O(S) memory itself — attention.  This
module implements FlashAttention-2 style tiled attention (forward +
backward as ``jax.custom_vjp``) with online softmax, f32 accumulation,
and MXU-aligned 128x128 tiles.

Everything here works on raw ``jnp`` arrays in **(B, H, S, D)** layout;
`flash_attention` adapts from the paddle **(B, S, H, D)** convention and
from the framework `Tensor` type.  On non-TPU backends the kernels run
in Pallas interpret mode so the exact same code path is testable on the
CPU mesh used by the test-suite.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# older jax (< 0.5) spells pltpu.CompilerParams as TPUCompilerParams;
# the kwargs we pass (dimension_semantics) exist under both names
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

__all__ = ["flash_attention", "mha", "mha_reference"]

_NEG_INF = -1e30
_LANES = 128


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _tile_keep_mask(seed, bh, qi, ki, block_q, block_k, p_drop):
    """Deterministic per-element keep mask for attention dropout.

    Counter-based hash (murmur3-finalizer rounds) over the element's
    GLOBAL (bh, q, k) coordinates, so the forward and both backward
    kernels regenerate the identical mask for a tile without ever
    materialising the (S, S) mask in HBM — the same trick the
    reference's vendored flashattn uses with its Philox offsets
    (``third_party/flashattn``). Plain vector int ops, so it runs the
    same on real TPU and in interpret mode (pltpu.prng_* has no
    interpret-mode lowering).
    """
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    def _i32(x):  # uint32 constant -> wrapped int32
        return jnp.int32(x - (1 << 32) if x >= (1 << 31) else x)

    h = rows * _i32(0x0001_93E9) + cols  # row-major element id, wraps
    h = h ^ seed ^ (bh * _i32(0x9E37_79B1))
    for mult in (_i32(0x85EB_CA6B), _i32(0xC2B2_AE35)):
        h = h * mult
        h = h ^ jax.lax.shift_right_logical(h, 15)
    u24 = jax.lax.shift_right_logical(h, 8)  # uniform in [0, 2^24)
    return u24 >= jnp.int32(int(p_drop * (1 << 24)))


def _interpret_default() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _sds(shape, dtype, like):
    """ShapeDtypeStruct whose varying-mesh-axes (vma) match ``like`` —
    required for pallas_call outputs under shard_map(check_vma=True)
    (ring attention runs the kernel inside shard_map)."""
    vma = None
    try:
        vma = jax.typeof(like).vma
    except Exception:
        pass
    if vma is not None:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _split_refs(refs, p_drop, has_lens, has_shift=False):
    """Peel the optional SMEM scalars (dropout seed, per-row kv lengths,
    traced causal shift) off the front of a kernel's ref list."""
    i = 0
    seed_ref = lens_ref = shift_ref = None
    if p_drop > 0.0:
        seed_ref, i = refs[0], 1
    if has_lens:
        lens_ref, i = refs[i], i + 1
    if has_shift:
        shift_ref, i = refs[i], i + 1
    return seed_ref, lens_ref, shift_ref, refs[i:]


def _key_mask(lens_ref, shift_ref, b, qi, ki, block_q, block_k, q_len,
              kv_len, causal):
    """Validity mask for one (block_q, block_k) tile.

    Fixed-length: keys < kv_len, causal diagonal offset kv_len - q_len
    (end-aligned cross attention). Varlen (lens_ref set): keys < lens[b]
    per row-of-batch, causal from position 0 (self-attention semantics —
    the reference's flash_attn_unpadded path). shift_ref (traced)
    overrides the causal diagonal offset — ring attention's per-step
    (my_rank - src_rank) * block shift.
    """
    shape = (block_q, block_k)
    kcol = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    if lens_ref is not None:
        mask = kcol < lens_ref[b]
        off = 0
    else:
        mask = kcol < kv_len
        off = kv_len - q_len
    if shift_ref is not None:
        off = shift_ref[0]
    if causal:
        qrow = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        mask = jnp.logical_and(mask, kcol <= qrow + off)
    return mask


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, causal, sm_scale, block_q, block_k, q_len, kv_len,
                p_drop, has_lens, has_shift):
    seed_ref, lens_ref, shift_ref, (q_ref, k_ref, v_ref, o_ref, lse_ref,
                                    m_scr, l_scr, acc_scr) = _split_refs(
        refs, p_drop, has_lens, has_shift)
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        # MXU contract: feed bf16 operands, accumulate fp32 via
        # preferred_element_type — an fp32 .astype before the dot would
        # run the MXU in fp32 mode at ~1/4 throughput (this exact
        # mistake cost 56% of the r03 GPT step, profile 2026-07-30)
        s = jax.lax.dot_general(q_ref[0], k_ref[0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale

        mask = _key_mask(lens_ref, shift_ref, b, qi, ki, block_q,
                         block_k, q_len, kv_len, causal)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        # l accumulates the UNdropped row sum (softmax denominator);
        # dropout applies to the numerator only: out = (p∘M/(1-r)) @ v / l
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if p_drop > 0.0:
            keep = _tile_keep_mask(seed_ref[0], b, qi, ki, block_q, block_k,
                                   p_drop)
            p = jnp.where(keep, p / (1.0 - p_drop), 0.0)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Blocks fully above the diagonal have nothing to attend to.
        _off = shift_ref[0] if shift_ref is not None else kv_len - q_len

        @pl.when(qi * block_q + block_q - 1 + _off >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # stats ride a trailing-singleton dim: block (block_q, 1) keeps the
        # TPU (8,128) tiling rule satisfied (block (1, block_q) on a 2-D
        # (BH, S) stats array does not lower on real hardware)
        lse_ref[0] = m_scr[:, :1] + jnp.log(l_safe)


def _seed_spec_args(seed, p_drop, lens, shift=None):
    """(extra in_specs, extra args) for the SMEM scalars: dropout seed,
    per-row kv lengths, traced causal shift. All cross the custom_vjp
    boundary as f32 bitcasts (custom_vjp needs a float cotangent slot per
    traced arg)."""
    specs, args = [], ()
    for val, want in ((seed, p_drop > 0.0), (lens, lens is not None),
                      (shift, shift is not None)):
        if want:
            v32 = jax.lax.bitcast_convert_type(val, jnp.int32).reshape(-1)
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            args += (v32,)
    return specs, args


def _fwd(q, k, v, seed, lens, shift, *, causal, sm_scale, block_q,
         block_k, q_len, kv_len, p_drop, interpret):
    bh, sq, d = q.shape
    skv = k.shape[1]
    nq, nk = sq // block_q, skv // block_k
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, q_len=q_len, kv_len=kv_len, p_drop=p_drop,
        has_lens=lens is not None, has_shift=shift is not None)
    seed_specs, seed_args = _seed_spec_args(seed, p_drop, lens, shift)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, sq, d), q.dtype, q),
            _sds((bh, sq, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_args, q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(*refs, causal, sm_scale, block_q, block_k,
                   q_len, kv_len, p_drop, has_lens, has_shift):
    seed_ref, lens_ref, shift_ref, (q_ref, k_ref, v_ref, do_ref, lse_ref,
                                    delta_ref, dq_ref,
                                    dq_scr) = _split_refs(
        refs, p_drop, has_lens, has_shift)
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        # bf16 operands into every dot; fp32 only for accumulators and
        # the softmax math (see the fwd kernel's MXU-contract note)
        s = jax.lax.dot_general(q_ref[0], k_ref[0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        mask = _key_mask(lens_ref, shift_ref, b, qi, ki, block_q,
                         block_k, q_len, kv_len, causal)
        p = jnp.exp(s - lse_ref[0])
        p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if p_drop > 0.0:
            # gradient flows only through kept elements (dp ∘ M/(1-r));
            # delta = rowsum(do∘out) already reflects the dropped forward
            keep = _tile_keep_mask(seed_ref[0], b, qi, ki, block_q, block_k,
                                   p_drop)
            dp = jnp.where(keep, dp / (1.0 - p_drop), 0.0)
        ds = p * (dp - delta_ref[0])
        dq_scr[:] = dq_scr[:] + sm_scale * jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        _off = shift_ref[0] if shift_ref is not None else kv_len - q_len

        @pl.when(qi * block_q + block_q - 1 + _off >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, causal, sm_scale, block_q, block_k, q_len,
                    kv_len, p_drop, has_lens, has_shift):
    seed_ref, lens_ref, shift_ref, (q_ref, k_ref, v_ref, do_ref, lse_ref,
                                    delta_ref, dk_ref, dv_ref, dk_scr,
                                    dv_scr) = _split_refs(
        refs, p_drop, has_lens, has_shift)
    b = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        # bf16 operands into every dot (see the fwd kernel's MXU note)
        s = jax.lax.dot_general(q_ref[0], k_ref[0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        mask = _key_mask(lens_ref, shift_ref, b, qi, ki, block_q,
                         block_k, q_len, kv_len, causal)
        p = jnp.exp(s - lse_ref[0])
        p = jnp.where(mask, p, 0.0)
        if p_drop > 0.0:
            keep = _tile_keep_mask(seed_ref[0], b, qi, ki, block_q, block_k,
                                   p_drop)
            inv = 1.0 / (1.0 - p_drop)
            p_tilde = jnp.where(keep, p * inv, 0.0)
        else:
            p_tilde = p
        # dv += p̃^T @ do (dropped probabilities fed the forward output)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p_tilde.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if p_drop > 0.0:
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - delta_ref[0])
        # dk += ds^T @ q
        dk_scr[:] = dk_scr[:] + sm_scale * jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        _off = shift_ref[0] if shift_ref is not None else kv_len - q_len

        @pl.when(qi * block_q + block_q - 1 + _off >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, seed, lens, shift, *, causal, sm_scale,
         block_q, block_k, q_len, kv_len, p_drop, interpret, dlse=None):
    bh, sq, d = q.shape
    skv = k.shape[1]
    nq, nk = sq // block_q, skv // block_k
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (bh, sq, 1)
    if dlse is not None:
        # d/ds of lse is p, so an lse cotangent folds into the delta
        # vector: ds = p∘(dp - (delta - dlse))
        delta = delta - dlse.astype(jnp.float32)
    seed_specs, seed_args = _seed_spec_args(seed, p_drop, lens, shift)
    has_lens = lens is not None
    has_shift = shift is not None

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, q_len=q_len,
                          kv_len=kv_len, p_drop=p_drop, has_lens=has_lens,
                          has_shift=has_shift),
        grid=(bh, nq, nk),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((bh, sq, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_args, q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, q_len=q_len,
                          kv_len=kv_len, p_drop=p_drop, has_lens=has_lens,
                          has_shift=has_shift),
        grid=(bh, nk, nq),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((bh, skv, d), k.dtype, k),
            _sds((bh, skv, d), v.dtype, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_args, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper on padded (BH, S, D) arrays
# ---------------------------------------------------------------------------
# seed / lens / shift are float32 (bitcast to int32 inside): custom_vjp
# needs a float cotangent slot for every traced arg, and the per-step
# dropout seed must be traced (a python int would retrace the train step
# every step). lens/shift=None are allowed: None is a static pytree.
_STATICS = (6, 7, 8, 9, 10, 11, 12, 13)


@functools.partial(jax.custom_vjp, nondiff_argnums=_STATICS)
def _flash(q, k, v, seed, lens, shift, causal, sm_scale, block_q, block_k,
           q_len, kv_len, p_drop, interpret):
    out, _ = _fwd(q, k, v, seed, lens, shift, causal=causal,
                  sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                  q_len=q_len, kv_len=kv_len, p_drop=p_drop,
                  interpret=interpret)
    return out


def _flash_fwd(q, k, v, seed, lens, shift, causal, sm_scale, block_q,
               block_k, q_len, kv_len, p_drop, interpret):
    out, lse = _fwd(q, k, v, seed, lens, shift, causal=causal,
                    sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                    q_len=q_len, kv_len=kv_len, p_drop=p_drop,
                    interpret=interpret)
    return out, (q, k, v, seed, lens, shift, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_len, kv_len, p_drop,
               interpret, res, do, dlse=None):
    q, k, v, seed, lens, shift, out, lse = res
    dq, dk, dv = _bwd(q, k, v, out, lse, do, seed, lens, shift,
                      causal=causal, sm_scale=sm_scale, block_q=block_q,
                      block_k=block_k, q_len=q_len, kv_len=kv_len,
                      p_drop=p_drop, interpret=interpret, dlse=dlse)
    return (dq, dk, dv, jnp.zeros((), jnp.float32),
            None if lens is None else jnp.zeros_like(lens),
            None if shift is None else jnp.zeros_like(shift))


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=_STATICS)
def _flash_lse(q, k, v, seed, lens, shift, causal, sm_scale, block_q,
               block_k, q_len, kv_len, p_drop, interpret):
    """(out, lse) variant for online-merge consumers (ring attention):
    the lse output is itself differentiable (d lse/d s = p folds into the
    backward delta vector)."""
    return _fwd(q, k, v, seed, lens, shift, causal=causal,
                sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                q_len=q_len, kv_len=kv_len, p_drop=p_drop,
                interpret=interpret)


def _flash_lse_fwd(q, k, v, seed, lens, shift, causal, sm_scale, block_q,
                   block_k, q_len, kv_len, p_drop, interpret):
    out, lse = _fwd(q, k, v, seed, lens, shift, causal=causal,
                    sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                    q_len=q_len, kv_len=kv_len, p_drop=p_drop,
                    interpret=interpret)
    return (out, lse), (q, k, v, seed, lens, shift, out, lse)


def _flash_lse_bwd(causal, sm_scale, block_q, block_k, q_len, kv_len,
                   p_drop, interpret, res, cots):
    do, dlse = cots
    return _flash_bwd(causal, sm_scale, block_q, block_k, q_len, kv_len,
                      p_drop, interpret, res, do, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
def _mha_tune_key(q, k, causal, interpret):
    return (q.shape[2], k.shape[2], q.shape[3], str(q.dtype), bool(causal),
            bool(interpret))


def mha(q, k, v, *, causal=False, sm_scale=None, block_q=None, block_k=None,
        dropout_p=0.0, seed=None, seq_lens=None, causal_shift=None,
        return_lse=False, interpret=None):
    """Tiled flash attention on raw arrays in (B, H, S, D) layout.

    Pads S to the tile size and D to the 128-lane width (zero-padding is
    exact: padded head dims contribute 0 to logits; padded keys are
    masked by ``kv_len``; padded query rows are sliced off).

    ``dropout_p`` > 0 applies attention-probability dropout INSIDE the
    kernel (counter-based mask regenerated in the backward — the
    reference's flash_attn dropout path, ``flash_attn_kernel.cu``);
    ``seed`` is a traced f32 scalar that must change per training step.

    ``block_q``/``block_k`` default to an autotuned choice when
    :func:`tune_mha` has cached one for this (seq, d, dtype, causal) key
    (ref ``paddle/phi/kernels/autotune/``), else 128/128.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, h, sq, d = q.shape
    skv = k.shape[2]
    if block_q is None and block_k is None:
        from . import autotune as _at
        hit = _at.cache_get("flash_mha", _mha_tune_key(
            q, k, causal, interpret)) if _at.enabled() else None
        if hit is not None:
            block_q, block_k = hit
    # explicitly passed blocks always win. Default: big q/k blocks —
    # on v5e the per-grid-step revisit overhead dominates below ~512,
    # measured 2026-07-30 at (8,16,1024,64): fwd+bwd 11.4ms at 128/128
    # vs 3.2ms at 1024/512 (exp/bench_flash.py)
    block_q = 1024 if block_q is None else block_q
    block_k = 512 if block_k is None else block_k
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, _ceil_to(sq, 8))
    block_k = min(block_k, _ceil_to(skv, 8))
    sq_p, skv_p = _ceil_to(sq, block_q), _ceil_to(skv, block_k)
    d_p = _ceil_to(d, _LANES)
    p_drop = float(dropout_p)
    if seed is None:
        seed = jnp.zeros((), jnp.float32)
    else:
        seed = jnp.asarray(seed, jnp.float32).reshape(())
    lens = None
    if seq_lens is not None:
        # per-sequence valid kv lengths (B,) -> (B*H,), f32-bitcast for
        # the custom_vjp boundary; varlen is self-attention semantics
        if sq != skv:
            raise ValueError("seq_lens requires self-attention (sq == skv)")
        l = jnp.asarray(seq_lens, jnp.int32).reshape(b)
        lens = jax.lax.bitcast_convert_type(
            jnp.repeat(l, h), jnp.float32)
    shift = None
    if causal_shift is not None:
        # traced diagonal offset (ring attention): col <= row + shift
        if not causal:
            raise ValueError("causal_shift requires causal=True")
        shift = jax.lax.bitcast_convert_type(
            jnp.asarray(causal_shift, jnp.int32).reshape(()), jnp.float32)

    def prep(x, s_p):
        x = x.reshape(b * h, x.shape[2], d)
        return jnp.pad(x, ((0, 0), (0, s_p - x.shape[1]), (0, d_p - d)))

    qp, kp, vp = prep(q, sq_p), prep(k, skv_p), prep(v, skv_p)
    if return_lse:
        out, lse = _flash_lse(qp, kp, vp, seed, lens, shift, causal,
                              sm_scale, block_q, block_k, sq, skv, p_drop,
                              interpret)
        return (out[:, :sq, :d].reshape(b, h, sq, d),
                lse[:, :sq, 0].reshape(b, h, sq))
    out = _flash(qp, kp, vp, seed, lens, shift, causal, sm_scale, block_q,
                 block_k, sq, skv, p_drop, interpret)
    return out[:, :sq, :d].reshape(b, h, sq, d)


def _mha_cost_fn(b, h, sq, skv, d, itemsize):
    """Per-candidate cost estimate for the flash-attention search:
    analytic FLOPs/bytes of the XLA reference (scaled from a small
    sample) order the survivors on the roofline; the vmem working set
    and MXU-fill checks reject configs before any timing."""
    from . import autotune as _at
    d_p = _ceil_to(d, _LANES)
    sb, ss = min(b * h, 4), min(sq, 256)
    sample = jnp.zeros((1, sb, ss, d), jnp.float32)
    seed = _at.analytic_seed(
        lambda a: mha_reference(a, a, a), sample)
    scale = (b * h * sq * skv) / max(sb * ss * ss, 1)
    flops = seed["flops"] * scale if seed else 4.0 * b * h * sq * skv * d
    bytes_ = seed["bytes"] * scale if seed else \
        4.0 * b * h * (sq + skv) * d * itemsize

    def cost(cfg):
        bq = min(int(cfg[0]), _ceil_to(sq, 8))
        bk = min(int(cfg[1]), _ceil_to(skv, 8))
        # per-grid-step tiles: q/o in native dtype + f32 acc, k/v
        # blocks, and the (bq, 128) m/l scratch rows
        vmem = (2 * bq * d_p * itemsize + bq * d_p * 4
                + 2 * bk * d_p * itemsize + 2 * bq * _LANES * 4)
        return {"flops": flops, "bytes": bytes_, "vmem_bytes": vmem,
                "mxu_underfill": min(bq, bk) < 8}
    return cost


def tune_mha(q, k, v, *, causal=False, interpret=None,
             candidates=((128, 128), (256, 256), (512, 256), (512, 512),
                         (1024, 256), (1024, 512))):
    """Warmup autotune for :func:`mha`: candidate (block_q, block_k)
    configs are pruned by the cost-model roofline (vmem overflow / MXU
    underfill rejected before timing — see :func:`autotune.search`),
    survivors are eagerly timed on REAL arrays, and the winner is cached
    keyed by (seq, d, dtype, causal) so subsequent (including traced)
    calls pick it up. Returns (best_config, timings). Candidates larger
    than the padded sequence are deduplicated after clamping."""
    from . import autotune as _at

    if interpret is None:
        interpret = _interpret_default()
    b, h, sq, d = q.shape
    skv = k.shape[2]
    seen, todo = set(), []
    for bq, bk in candidates:
        clamped = (min(bq, _ceil_to(sq, 8)), min(bk, _ceil_to(skv, 8)))
        if clamped not in seen:
            seen.add(clamped)
            todo.append(clamped)

    state = {"q": q}

    def run(cfg):
        # thread the output back in (fresh inputs per call) and fence
        # with a host readback: remote-device backends can both cache
        # identical repeated executions and no-op block_until_ready,
        # which would make every candidate time the same
        out = mha(state["q"], k, v, causal=causal, block_q=cfg[0],
                  block_k=cfg[1], interpret=interpret)
        state["q"] = (out.astype(jnp.float32) * 1e-3).astype(q.dtype)
        float(jnp.sum(state["q"].astype(jnp.float32)))

    best, timings = _at.search(
        "flash_mha", _mha_tune_key(q, k, causal, interpret), run, todo,
        cost=_mha_cost_fn(b, h, sq, skv, d, q.dtype.itemsize))
    # explicit tuning is intent: turn cache consumption on (still
    # switch-offable via incubate.autotune.set_config kernel.enable=False)
    _at.set_enabled(True)
    return best, timings


def mha_reference(q, k, v, *, causal=False, sm_scale=None):
    """Plain-XLA reference used by the kernel unit tests ((B,H,S,D))."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, skv = s.shape[-2], s.shape[-1]
        qrow = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 0)
        kcol = jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 1)
        s = jnp.where(kcol <= qrow + (skv - sq), s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(
        q.dtype)


def flash_attention(query, key, value, *, causal=False, dropout_p=0.0,
                    interpret=None):
    """Framework-facing entry: paddle (B, S, H, D) layout, Tensor in/out.

    TPU replacement for the reference's flash_attn path
    (``python/paddle/nn/functional/flash_attention.py`` →
    ``paddle/phi/kernels/gpu/flash_attn_kernel.cu``), incl. its dropout
    support. The per-call dropout seed draws from the framework
    generator, so it folds from the trace key under jit (fresh mask
    every compiled step) and from host state in eager mode.
    """
    from .op_utils import ensure_tensor, nary
    from ..framework import random as _random

    q, k, v = (ensure_tensor(t) for t in (query, key, value))
    inputs = [q, k, v]
    if dropout_p > 0.0:
        key_seed = jax.random.bits(_random.next_key(), (),
                                   jnp.uint32).astype(jnp.int32)
        seed_f32 = jax.lax.bitcast_convert_type(key_seed, jnp.float32)
        inputs.append(ensure_tensor(seed_f32))

    def f(qd, kd, vd, *rest):
        o = mha(jnp.swapaxes(qd, 1, 2), jnp.swapaxes(kd, 1, 2),
                jnp.swapaxes(vd, 1, 2), causal=causal,
                dropout_p=dropout_p, seed=rest[0] if rest else None,
                interpret=interpret)
        return jnp.swapaxes(o, 1, 2)

    return nary(f, inputs, name="flash_attention")


# ---------------------------------------------------------------------------
# packed (ragged varlen) flash attention
# ---------------------------------------------------------------------------
# True varlen: sequences stay PACKED (total_tokens, H, D) — no pad-to-max
# batch. Each sequence is block-aligned inside a packed buffer so every
# (block_q, block_k) tile belongs to exactly one sequence; per-q-block
# [klo, khi] (and per-k-block [qlo, qhi]) SMEM ranges skip everything off
# the block-diagonal band. Compute scales as sum(len_i * len_j-of-own-seq)
# = O(sum len^2), the true ragged cost, instead of the padded path's
# O(B * max_len^2). Cross-attention lengths (cu_q != cu_k) are supported;
# causal uses the bottom-right alignment (col_pos <= row_pos + len_k -
# len_q), the flash-attn varlen convention.
# Ref: ``python/paddle/nn/functional/flash_attention.py:272`` over
# ``third_party/flashattn`` cu_seqlens grids.

def _packed_mask(pq_ref, okq_ref, off_ref, pk_ref, okk_ref, causal,
                 block_q, block_k):
    pq = pq_ref[:, :1]                        # (bq, 1) int32
    okq = okq_ref[:, :1] > 0
    pk = pk_ref[:, 0][None, :]                # (1, bk)
    okk = okk_ref[:, 0][None, :] > 0
    mask = jnp.logical_and(okq, okk)
    if causal:
        off = off_ref[:, :1]
        mask = jnp.logical_and(mask, pk <= pq + off)
    return mask


def _pk_fwd_kernel(*refs, causal, sm_scale, block_q, block_k, p_drop):
    i = 1 if p_drop > 0.0 else 0
    seed_ref = refs[0] if p_drop > 0.0 else None
    (klo_ref, khi_ref, q_ref, k_ref, v_ref, pq_ref, okq_ref, off_ref,
     pk_ref, okk_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs[i:]
    h = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        s = jax.lax.dot_general(q_ref[0], k_ref[0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        mask = _packed_mask(pq_ref, okq_ref, off_ref, pk_ref, okk_ref,
                            causal, block_q, block_k)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if p_drop > 0.0:
            keep = _tile_keep_mask(seed_ref[0], h, qi, ki, block_q,
                                   block_k, p_drop)
            p = jnp.where(keep, p / (1.0 - p_drop), 0.0)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(jnp.logical_and(ki >= klo_ref[qi], ki <= khi_ref[qi]))
    def _():
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:, :1] + jnp.log(l_safe)


def _pk_bwd_dq_kernel(*refs, causal, sm_scale, block_q, block_k, p_drop):
    i = 1 if p_drop > 0.0 else 0
    seed_ref = refs[0] if p_drop > 0.0 else None
    (klo_ref, khi_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     pq_ref, okq_ref, off_ref, pk_ref, okk_ref, dq_ref,
     dq_scr) = refs[i:]
    h = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        s = jax.lax.dot_general(q_ref[0], k_ref[0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        mask = _packed_mask(pq_ref, okq_ref, off_ref, pk_ref, okk_ref,
                            causal, block_q, block_k)
        p = jnp.exp(s - lse_ref[0])
        p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if p_drop > 0.0:
            keep = _tile_keep_mask(seed_ref[0], h, qi, ki, block_q,
                                   block_k, p_drop)
            dp = jnp.where(keep, dp / (1.0 - p_drop), 0.0)
        ds = p * (dp - delta_ref[0])
        dq_scr[:] = dq_scr[:] + sm_scale * jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(ki >= klo_ref[qi], ki <= khi_ref[qi]))
    def _():
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _pk_bwd_dkv_kernel(*refs, causal, sm_scale, block_q, block_k, p_drop):
    i = 1 if p_drop > 0.0 else 0
    seed_ref = refs[0] if p_drop > 0.0 else None
    (qlo_ref, qhi_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     pq_ref, okq_ref, off_ref, pk_ref, okk_ref, dk_ref, dv_ref, dk_scr,
     dv_scr) = refs[i:]
    h = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        s = jax.lax.dot_general(q_ref[0], k_ref[0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        mask = _packed_mask(pq_ref, okq_ref, off_ref, pk_ref, okk_ref,
                            causal, block_q, block_k)
        p = jnp.exp(s - lse_ref[0])
        p = jnp.where(mask, p, 0.0)
        if p_drop > 0.0:
            keep = _tile_keep_mask(seed_ref[0], h, qi, ki, block_q,
                                   block_k, p_drop)
            inv = 1.0 / (1.0 - p_drop)
            p_tilde = jnp.where(keep, p * inv, 0.0)
        else:
            p_tilde = p
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p_tilde.astype(do_ref.dtype), do_ref[0],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if p_drop > 0.0:
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - delta_ref[0])
        dk_scr[:] = dk_scr[:] + sm_scale * jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(qi >= qlo_ref[ki], qi <= qhi_ref[ki]))
    def _():
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _pk_fwd(q, k, v, seed, meta, *, causal, sm_scale, block_q, block_k,
            p_drop, interpret):
    """q/k/v: (H, CapQ/K, D). meta: int32 arrays (see mha_packed)."""
    pos_q, ok_q, off_q, pos_k, ok_k, klo, khi, qlo, qhi = meta
    H, capq, d = q.shape
    capk = k.shape[1]
    nq, nk = capq // block_q, capk // block_k
    seed_specs, seed_args = (([pl.BlockSpec(memory_space=pltpu.SMEM)],
                              (jax.lax.bitcast_convert_type(
                                  seed, jnp.int32).reshape(-1),))
                             if p_drop > 0.0 else ([], ()))
    row_spec_q = pl.BlockSpec((block_q, 1), lambda h, i, j: (i, 0))
    row_spec_k = pl.BlockSpec((block_k, 1), lambda h, i, j: (j, 0))
    out, lse = pl.pallas_call(
        functools.partial(_pk_fwd_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, p_drop=p_drop),
        grid=(H, nq, nk),
        in_specs=seed_specs + [
            pl.BlockSpec(memory_space=pltpu.SMEM),   # klo
            pl.BlockSpec(memory_space=pltpu.SMEM),   # khi
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
            row_spec_q, row_spec_q, row_spec_q,
            row_spec_k, row_spec_k,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda h, i, j: (h, i, 0)),
        ],
        out_shape=[
            _sds((H, capq, d), q.dtype, q),
            _sds((H, capq, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_args, klo, khi, q, k, v, pos_q[:, None], ok_q[:, None],
      off_q[:, None], pos_k[:, None], ok_k[:, None])
    return out, lse


def _pk_bwd(q, k, v, out, lse, do, seed, meta, *, causal, sm_scale,
            block_q, block_k, p_drop, interpret):
    pos_q, ok_q, off_q, pos_k, ok_k, klo, khi, qlo, qhi = meta
    H, capq, d = q.shape
    capk = k.shape[1]
    nq, nk = capq // block_q, capk // block_k
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)
    seed_specs, seed_args = (([pl.BlockSpec(memory_space=pltpu.SMEM)],
                              (jax.lax.bitcast_convert_type(
                                  seed, jnp.int32).reshape(-1),))
                             if p_drop > 0.0 else ([], ()))
    row_q = pl.BlockSpec((block_q, 1), lambda h, i, j: (i, 0))
    row_k = pl.BlockSpec((block_k, 1), lambda h, i, j: (j, 0))
    dq = pl.pallas_call(
        functools.partial(_pk_bwd_dq_kernel, causal=causal,
                          sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, p_drop=p_drop),
        grid=(H, nq, nk),
        in_specs=seed_specs + [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda h, i, j: (h, i, 0)),
            row_q, row_q, row_q, row_k, row_k,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
        out_shape=_sds((H, capq, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_args, klo, khi, q, k, v, do, lse, delta, pos_q[:, None],
      ok_q[:, None], off_q[:, None], pos_k[:, None], ok_k[:, None])

    row_q2 = pl.BlockSpec((block_q, 1), lambda h, j, i: (i, 0))
    row_k2 = pl.BlockSpec((block_k, 1), lambda h, j, i: (j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_pk_bwd_dkv_kernel, causal=causal,
                          sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, p_drop=p_drop),
        grid=(H, nk, nq),
        in_specs=seed_specs + [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda h, j, i: (h, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda h, j, i: (h, i, 0)),
            row_q2, row_q2, row_q2, row_k2, row_k2,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),
        ],
        out_shape=[
            _sds((H, capk, d), k.dtype, k),
            _sds((H, capk, d), v.dtype, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*seed_args, qlo, qhi, q, k, v, do, lse, delta, pos_q[:, None],
      ok_q[:, None], off_q[:, None], pos_k[:, None], ok_k[:, None])
    return dq, dk, dv


_PK_STATICS = tuple(range(13, 19))


@functools.partial(jax.custom_vjp, nondiff_argnums=_PK_STATICS)
def _pk_flash(q, k, v, seed, pos_q, ok_q, off_q, pos_k, ok_k, klo, khi,
              qlo, qhi, causal, sm_scale, block_q, block_k, p_drop,
              interpret):
    out, _ = _pk_fwd(q, k, v, seed,
                     (pos_q, ok_q, off_q, pos_k, ok_k, klo, khi, qlo, qhi),
                     causal=causal, sm_scale=sm_scale, block_q=block_q,
                     block_k=block_k, p_drop=p_drop, interpret=interpret)
    return out


def _pk_flash_fwd(q, k, v, seed, pos_q, ok_q, off_q, pos_k, ok_k, klo, khi,
                  qlo, qhi, causal, sm_scale, block_q, block_k, p_drop,
                  interpret):
    meta = (pos_q, ok_q, off_q, pos_k, ok_k, klo, khi, qlo, qhi)
    out, lse = _pk_fwd(q, k, v, seed, meta, causal=causal,
                       sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                       p_drop=p_drop, interpret=interpret)
    return out, (q, k, v, seed, meta, out, lse)


def _pk_flash_bwd(causal, sm_scale, block_q, block_k, p_drop, interpret,
                  res, do):
    q, k, v, seed, meta, out, lse = res
    dq, dk, dv = _pk_bwd(q, k, v, out, lse, do, seed, meta, causal=causal,
                         sm_scale=sm_scale, block_q=block_q,
                         block_k=block_k, p_drop=p_drop,
                         interpret=interpret)
    zmeta = tuple(jnp.zeros_like(m) for m in meta)
    return (dq, dk, dv, jnp.zeros((), jnp.float32)) + zmeta


_pk_flash.defvjp(_pk_flash_fwd, _pk_flash_bwd)


def mha_packed(q, k, v, cu_q, cu_k, *, causal=False, sm_scale=None,
               dropout_p=0.0, seed=None, block_q=None, block_k=None,
               interpret=None):
    """Ragged varlen flash attention over PACKED tokens.

    q: (total_q, H, D); k/v: (total_k, H, D); cu_q/cu_k: (B+1,) int32
    cumulative lengths (may be traced). Cross-attention lengths
    (cu_q != cu_k) are supported; ``causal`` uses bottom-right alignment
    within each pair (col_pos <= row_pos + len_k - len_q).

    Each sequence is block-aligned inside a static-capacity packed
    buffer; the kernels skip all tiles outside each block's own
    sequence, so compute is O(sum_i lq_i * lk_i), not O(B * max^2).
    """
    if interpret is None:
        interpret = _interpret_default()
    total_q, H, d_in = q.shape
    total_k = k.shape[0]
    B = cu_q.shape[0] - 1
    bq = 512 if block_q is None else block_q
    bk = 512 if block_k is None else block_k
    bq = min(bq, _ceil_to(total_q, 8))
    bk = min(bk, _ceil_to(total_k, 8))
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d_in)
    d = _ceil_to(d_in, _LANES)
    capq = (total_q + B * bq + bq - 1) // bq * bq
    capk = (total_k + B * bk + bk - 1) // bk * bk
    nq, nk = capq // bq, capk // bk
    i32 = jnp.int32
    cu_q = jnp.asarray(cu_q, i32)
    cu_k = jnp.asarray(cu_k, i32)
    lens_q = cu_q[1:] - cu_q[:-1]
    lens_k = cu_k[1:] - cu_k[:-1]
    plen_q = (lens_q + bq - 1) // bq * bq
    plen_k = (lens_k + bk - 1) // bk * bk
    starts_q = jnp.concatenate([jnp.zeros(1, i32),
                                jnp.cumsum(plen_q)])[:-1]
    starts_k = jnp.concatenate([jnp.zeros(1, i32),
                                jnp.cumsum(plen_k)])[:-1]
    off_seq = lens_k - lens_q  # bottom-right causal alignment

    def pack_meta(total, cap, cu, starts, lens, offs):
        tok = jnp.arange(total, dtype=i32)
        s_of = jnp.clip(jnp.searchsorted(cu, tok, side="right") - 1,
                        0, B - 1)
        newpos = starts[s_of] + tok - cu[s_of]
        r = jnp.arange(cap, dtype=i32)
        sp = jnp.clip(jnp.searchsorted(starts, r, side="right") - 1,
                      0, B - 1)
        local = r - starts[sp]
        valid = local < lens[sp]
        pos = jnp.where(valid, local, -1)
        return newpos, pos, valid.astype(i32), offs[sp]

    def scatter(x, cap, newpos):
        buf = jnp.zeros((cap, H, d), x.dtype)
        xp = jnp.pad(x, ((0, 0), (0, 0), (0, d - d_in)))
        return jnp.swapaxes(buf.at[newpos].set(xp), 0, 1)

    newpos_q, pos_q, ok_q, off_q = pack_meta(
        total_q, capq, cu_q, starts_q, lens_q, off_seq)
    newpos_k, pos_k, ok_k, _ = pack_meta(
        total_k, capk, cu_k, starts_k, lens_k, off_seq)
    qp = scatter(q, capq, newpos_q)
    kp = scatter(k, capk, newpos_k)
    vp = scatter(v, capk, newpos_k)  # k and v share the packing

    # per-q-block k ranges
    rb = jnp.arange(nq, dtype=i32) * bq
    sb = jnp.clip(jnp.searchsorted(starts_q, rb, side="right") - 1,
                  0, B - 1)
    has_data = rb < starts_q[sb] + plen_q[sb]
    klo = jnp.where(has_data, starts_k[sb] // bk, 1)
    khi_full = jnp.where(has_data,
                         (starts_k[sb] + plen_k[sb] - 1) // bk, 0)
    if causal:
        end_local = rb + bq - 1 - starts_q[sb]
        kcol_max = starts_k[sb] + end_local + off_seq[sb]
        khi = jnp.where(kcol_max >= starts_k[sb],
                        jnp.minimum(khi_full, kcol_max // bk), 0)
        khi = jnp.where(has_data, khi, 0)
        klo = jnp.where(jnp.logical_and(has_data,
                                        kcol_max >= starts_k[sb]),
                        klo, 1)
    else:
        khi = khi_full
    # per-k-block q ranges (dkv)
    rk = jnp.arange(nk, dtype=i32) * bk
    sk = jnp.clip(jnp.searchsorted(starts_k, rk, side="right") - 1,
                  0, B - 1)
    has_k = rk < starts_k[sk] + plen_k[sk]
    qlo_full = jnp.where(has_k, starts_q[sk] // bq, 1)
    qhi = jnp.where(has_k, (starts_q[sk] + plen_q[sk] - 1) // bq, 0)
    if causal:
        qmin_global = starts_q[sk] + (rk - starts_k[sk]) - off_seq[sk]
        qmin_global = jnp.maximum(qmin_global, starts_q[sk])
        qlo = jnp.maximum(qlo_full, qmin_global // bq)
        qlo = jnp.where(has_k, qlo, 1)
    else:
        qlo = qlo_full

    if seed is None:
        seed = jnp.zeros((), jnp.float32)
    else:
        seed = jnp.asarray(seed, jnp.float32).reshape(())
    out = _pk_flash(qp, kp, vp, seed, pos_q, ok_q, off_q, pos_k, ok_k,
                    klo, khi, qlo, qhi, causal, sm_scale, bq, bk,
                    float(dropout_p), interpret)
    out = jnp.swapaxes(out, 0, 1)                 # (capq, H, D)
    return out[newpos_q][:, :, :d_in]             # packed (total_q, H, D)
