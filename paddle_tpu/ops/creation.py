"""Tensor creation ops (ref: ``python/paddle/tensor/creation.py``)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, to_tensor  # noqa: F401
from ..framework.dtype import to_jax_dtype, default_jax_dtype
from ..framework import random as _random
from .op_utils import ensure_tensor, unary

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "tril", "triu", "tril_indices", "triu_indices", "meshgrid",
    "diag", "diagflat", "diag_embed", "assign", "clone", "rand", "randn",
    "randint", "randint_like", "randperm", "uniform", "normal",
    "standard_normal", "bernoulli", "multinomial", "poisson", "exponential_",
    "uniform_", "normal_", "complex", "polar", "as_tensor",
    "create_parameter", "check_shape",
]


def _dt(dtype):
    return to_jax_dtype(dtype) if dtype is not None else default_jax_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data if isinstance(s, Tensor) else s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), dtype=_dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), dtype=_dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        # keep the fill value on device: jnp.full takes 0-d arrays, so
        # no host sync and no tracer crash when called under jit
        fill_value = fill_value._data.reshape(())
    if dtype is None:
        return Tensor(jnp.full(_shape(shape), fill_value))
    return Tensor(jnp.full(_shape(shape), fill_value, dtype=to_jax_dtype(dtype)))


def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    dt = to_jax_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.zeros_like(x._data, dtype=dt))


def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    dt = to_jax_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.ones_like(x._data, dtype=dt))


def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    dt = to_jax_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.full_like(x._data, fill_value, dtype=dt))


def empty(shape, dtype=None, name=None):
    # XLA has no uninitialized memory; zeros is the deterministic choice.
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    dt = to_jax_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    dt = to_jax_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=dt))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    dt = to_jax_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)),
                               base=_v(base), dtype=dt))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def tril(x, diagonal=0, name=None):
    return unary(lambda d: jnp.tril(d, k=diagonal), x, name="tril")


def triu(x, diagonal=0, name=None):
    return unary(lambda d: jnp.triu(d, k=diagonal), x, name="triu")


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(
        to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(
        to_jax_dtype(dtype)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    arrs = jnp.meshgrid(*[ensure_tensor(a)._data for a in args], indexing="ij")
    return [Tensor(a) for a in arrs]


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)
    if x.ndim == 1 and padding_value != 0:
        def f(d):
            n = d.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, dtype=d.dtype)
            idx = jnp.arange(d.shape[0])
            return out.at[idx + max(-offset, 0), idx + max(offset, 0)].set(d)
        return unary(f, x, name="diag")
    return unary(lambda d: jnp.diag(d, k=offset), x, name="diag")


def diagflat(x, offset=0, name=None):
    return unary(lambda d: jnp.diagflat(d, k=offset), x, name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def f(d):
        n = d.shape[-1] + abs(offset)
        base = jnp.zeros(d.shape[:-1] + (n, n), dtype=d.dtype)
        idx = jnp.arange(d.shape[-1])
        out = base.at[..., idx + max(-offset, 0), idx + max(offset, 0)].set(d)
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # place the two diag dims at dim1/dim2
        order = perm.copy()
        for pos, axis in sorted([(d1, nd - 2), (d2, nd - 1)]):
            order.insert(pos, axis)
        return jnp.transpose(out, order)
    return unary(f, x, name="diag_embed")


def assign(x, output=None):
    x = ensure_tensor(x) if not isinstance(x, (np.ndarray, list, tuple, int, float)) \
        else Tensor(jnp.asarray(x))
    out = unary(jnp.copy, x, name="assign")
    if output is not None:
        output.set_value(out._data)
        return output
    return out


def clone(x, name=None):
    return ensure_tensor(x).clone()


# -- random creation --------------------------------------------------------
def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(_random.next_key(), _shape(shape),
                                     dtype=_dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(_random.next_key(), _shape(shape),
                                    dtype=_dt(dtype)))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_random.next_key(), _shape(shape),
                                     low, high, dtype=to_jax_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_random.next_key(),
                                         jnp.arange(n, dtype=to_jax_dtype(dtype))))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else _random.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=_dt(dtype),
                                     minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = ensure_tensor(mean)._data if isinstance(mean, Tensor) else mean
        s = ensure_tensor(std)._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(_random.next_key(), shp,
                                        dtype=default_jax_dtype()) * s + m)
    return Tensor(jax.random.normal(_random.next_key(), _shape(shape),
                                    dtype=default_jax_dtype()) * std + mean)


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.random.bernoulli(_random.next_key(),
                                       x._data).astype(x._data.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    key = _random.next_key()
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if x.ndim == 1:
        out = jax.random.choice(key, x._data.shape[0], (num_samples,),
                                replace=replacement, p=x._data / x._data.sum())
    else:
        keys = jax.random.split(key, x._data.shape[0])
        out = jnp.stack([
            jax.random.choice(k, x._data.shape[1], (num_samples,),
                              replace=replacement, p=row / row.sum())
            for k, row in zip(keys, x._data)])
    return Tensor(out.astype(jnp.int32))


def poisson(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.random.poisson(_random.next_key(), x._data).astype(
        x._data.dtype))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(_random.next_key(), x._data.shape,
                                 dtype=x._data.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (jax.random.normal(_random.next_key(), x._data.shape,
                                 dtype=x._data.dtype) * std + mean)
    return x


def exponential_(x, lam=1.0, name=None):
    x._data = jax.random.exponential(
        _random.next_key(), x._data.shape, dtype=x._data.dtype) / lam
    return x


def complex(real, imag, name=None):
    from .op_utils import binary
    return binary(jax.lax.complex, real, imag, name="complex")


def polar(abs, angle, name=None):
    from .op_utils import binary
    return binary(lambda a, t: jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t)),
                  abs, angle, name="polar")


def as_tensor(data, dtype=None, place=None):
    return to_tensor(data, dtype=dtype, place=place)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Standalone parameter factory (ref: ``tensor/creation.py
    create_parameter``): Xavier-normal weights / zero biases by default,
    honoring ``ParamAttr`` and ``LazyGuard`` (same path as
    ``Layer.create_parameter``)."""
    from ..nn.layer.layers import make_parameter, ParamAttr
    if attr is None and name is not None:
        attr = ParamAttr(name=name)
    return make_parameter(_shape(shape), attr=attr, dtype=dtype,
                          is_bias=is_bias,
                          default_initializer=default_initializer)


def check_shape(shape):
    """Validate a shape argument before creation ops (ref:
    ``utils/layers_utils.py:463``)."""
    if isinstance(shape, Tensor):
        if np.dtype(shape._data.dtype).kind not in "iu":
            raise TypeError("shape tensor must be int32/int64")
        return
    for ele in shape:
        if isinstance(ele, Tensor):
            continue
        if not isinstance(ele, (int, np.integer)):
            raise TypeError(
                "All elements in `shape` must be integers when it's a "
                "list or tuple")
        if ele < 0:
            raise ValueError(
                "All elements in `shape` must be positive when it's a "
                "list or tuple")
