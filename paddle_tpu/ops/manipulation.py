"""Shape / layout manipulation ops (ref: ``python/paddle/tensor/manipulation.py``).

All of these are metadata ops or gathers in XLA — reshape/transpose are free
inside a fused computation; only gathers/scatters materialise data movement.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework.dtype import to_jax_dtype
from .op_utils import ensure_tensor, unary as _unary, binary as _binary, nary

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "transpose", "moveaxis", "swapaxes", "concat", "stack",
    "hstack", "vstack", "dstack", "split", "vsplit", "hsplit", "dsplit",
    "chunk", "tile", "expand", "expand_as", "broadcast_to", "broadcast_tensors",
    "gather", "gather_nd", "scatter", "scatter_", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add",
    "index_add_", "index_put", "index_put_",
    "take_along_axis", "put_along_axis", "roll", "flip", "rot90", "unbind",
    "unstack", "repeat_interleave", "slice", "strided_slice", "crop", "pad",
    "t", "as_real", "as_complex", "view", "view_as", "atleast_1d",
    "atleast_2d", "atleast_3d", "tensordot", "flatten_", "masked_fill",
    "masked_select", "masked_scatter", "where", "tolist", "numel", "rank",
    "shard_index", "tensor_split", "unflatten", "as_strided", "unfold",
    "reverse", "shape",
]


def _shape_vals(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy().tolist())
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def reshape(x, shape, name=None):
    shp = _shape_vals(shape)
    return _unary(lambda d: jnp.reshape(d, shp), x, name="reshape")


def reshape_(x, shape, name=None):
    return _rebind(x, reshape(x, shape))


view = reshape


def view_as(x, other, name=None):
    return reshape(x, ensure_tensor(other).shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def f(d):
        shp = d.shape[:s] + (-1,) + d.shape[e + 1:]
        return jnp.reshape(d, shp)
    return _unary(f, x, name="flatten")


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return _rebind(x, flatten(x, start_axis, stop_axis))


from ..autograd import rebind_inplace as _rebind  # noqa: E402


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return _unary(lambda d: jnp.squeeze(d, axis=ax), x, name="squeeze")


def squeeze_(x, axis=None, name=None):
    return _rebind(x, squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes]

    def f(d):
        out = d
        nd = d.ndim + len(axes)
        for a in sorted([a % nd for a in axes]):
            out = jnp.expand_dims(out, a)
        return out
    return _unary(f, x, name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    return _rebind(x, unsqueeze(x, axis))


def transpose(x, perm, name=None):
    p = tuple(int(v) for v in perm)
    return _unary(lambda d: jnp.transpose(d, p), x, name="transpose")


def moveaxis(x, source, destination, name=None):
    return _unary(lambda d: jnp.moveaxis(d, source, destination), x,
                  name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return _unary(lambda d: jnp.swapaxes(d, axis0, axis1), x, name="swapaxes")


def t(x, name=None):
    x = ensure_tensor(x)
    if x.ndim > 2:
        raise ValueError("paddle.t only supports ndim <= 2")
    return _unary(jnp.transpose, x, name="t")


def concat(x, axis=0, name=None):
    tensors = [ensure_tensor(v) for v in x]
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return nary(lambda *ds: jnp.concatenate(ds, axis=ax), tensors,
                name="concat")


def stack(x, axis=0, name=None):
    tensors = [ensure_tensor(v) for v in x]
    return nary(lambda *ds: jnp.stack(ds, axis=axis), tensors, name="stack")


def hstack(x, name=None):
    return nary(lambda *ds: jnp.hstack(ds), [ensure_tensor(v) for v in x],
                name="hstack")


def vstack(x, name=None):
    return nary(lambda *ds: jnp.vstack(ds), [ensure_tensor(v) for v in x],
                name="vstack")


def dstack(x, name=None):
    return nary(lambda *ds: jnp.dstack(ds), [ensure_tensor(v) for v in x],
                name="dstack")


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    ax = ax % x.ndim
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {ax} (size {dim}) is not divisible by "
                f"num={num_or_sections}; pass explicit section sizes")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_minus = sum(1 for s in sizes if s < 0)
        if n_minus:
            rem = dim - sum(s for s in sizes if s >= 0)
            sizes = [rem if s < 0 else s for s in sizes]
    offsets = np.cumsum([0] + sizes)
    outs = []
    for i in range(len(sizes)):
        sl = [np.s_[:]] * x.ndim
        sl[ax] = np.s_[int(offsets[i]):int(offsets[i + 1])]
        outs.append(_unary(lambda d, sl=tuple(sl): d[sl], x, name="split"))
    return outs


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = ensure_tensor(x)
    arrs = jnp.array_split(x._data, num_or_indices, axis=axis) \
        if isinstance(num_or_indices, int) else \
        jnp.split(x._data, num_or_indices, axis=axis)
    # route through split-style recording for grad support
    outs = []
    start = 0
    ax = axis % x.ndim
    for a in arrs:
        n = a.shape[ax]
        sl = [np.s_[:]] * x.ndim
        sl[ax] = np.s_[start:start + n]
        outs.append(_unary(lambda d, sl=tuple(sl): d[sl], x, name="tensor_split"))
        start += n
    return outs


def vsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=0)


def hsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=1)


def dsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0, name=None):
    x = ensure_tensor(x)
    n = x.shape[axis % x.ndim]
    return [_unary(lambda d, i=i: jnp.squeeze(
                jax.lax.slice_in_dim(d, i, i + 1, axis=axis % d.ndim),
                axis=axis % d.ndim), x, name="unbind")
            for i in range(n)]


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _shape_vals(repeat_times)
    return _unary(lambda d: jnp.tile(d, reps), x, name="tile")


def expand(x, shape, name=None):
    shp = _shape_vals(shape)
    x = ensure_tensor(x)

    def f(d):
        tgt = list(shp)
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = d.shape[i - len(tgt) + d.ndim]
        return jnp.broadcast_to(d, tuple(tgt))
    return _unary(f, x, name="expand")


def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    datas = [ensure_tensor(t)._data for t in inputs]
    shp = jnp.broadcast_shapes(*[d.shape for d in datas])
    return [expand(t, shp) for t in inputs]


def atleast_1d(*inputs, name=None):
    outs = [_unary(jnp.atleast_1d, x, name="atleast_1d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [_unary(jnp.atleast_2d, x, name="atleast_2d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [_unary(jnp.atleast_3d, x, name="atleast_3d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


# -- gathers / scatters -----------------------------------------------------
def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return nary(lambda d, i: jnp.take(d, i.astype(jnp.int32).ravel(), axis=ax),
                [x, ensure_tensor(index)], name="gather")


def gather_nd(x, index, name=None):
    def f(d, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        return d[tuple(jnp.moveaxis(idx, -1, 0))] if k == d.ndim else \
            d[tuple(jnp.moveaxis(idx, -1, 0))]
    return nary(f, [x, ensure_tensor(index)], name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(d, i, u):
        i = i.astype(jnp.int32).ravel()
        if overwrite:
            return d.at[i].set(u)
        return d.at[i].set(0).at[i].add(u)
    return nary(f, [x, ensure_tensor(index), ensure_tensor(updates)],
                name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    return _rebind(x, scatter(x, index, updates, overwrite))


def scatter_nd(index, updates, shape, name=None):
    shp = _shape_vals(shape)

    def f(i, u):
        z = jnp.zeros(shp, dtype=u.dtype)
        return z.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)
    return nary(f, [ensure_tensor(index), ensure_tensor(updates)],
                name="scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    def f(d, i, u):
        return d.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)
    return nary(f, [x, ensure_tensor(index), ensure_tensor(updates)],
                name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    return nary(lambda d, i: jnp.take(d, i.astype(jnp.int32).ravel(), axis=axis),
                [x, ensure_tensor(index)], name="index_select")


def index_sample(x, index, name=None):
    def f(d, i):
        return jnp.take_along_axis(d, i.astype(jnp.int32), axis=1)
    return nary(f, [x, ensure_tensor(index)], name="index_sample")


def index_add(x, index, axis, value, name=None):
    def f(d, i, v):
        i = i.astype(jnp.int32)
        dm = jnp.moveaxis(d, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = dm.at[i].add(vm)
        return jnp.moveaxis(out, 0, axis)
    return nary(f, [x, ensure_tensor(index), ensure_tensor(value)],
                name="index_add")


def index_add_(x, index, axis, value, name=None):
    """In-place ``index_add`` (ref
    ``python/paddle/tensor/manipulation.py:4502``): embedding surgery /
    KV-cache writes mutate the tensor, tape linkage rebinds."""
    return _rebind(x, index_add(x, index, axis, value))


def index_put(x, indices, value, accumulate=False, name=None):
    idx_tensors = [ensure_tensor(i) for i in indices]

    def f(d, v, *idxs):
        key = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer)
                    else i for i in idxs)
        return d.at[key].add(v) if accumulate else d.at[key].set(v)
    return nary(f, [x, ensure_tensor(value)] + idx_tensors, name="index_put")


def index_put_(x, indices, value, accumulate=False, name=None):
    """In-place ``index_put`` (ref
    ``python/paddle/tensor/manipulation.py:4633``)."""
    return _rebind(x, index_put(x, indices, value, accumulate))


def take_along_axis(x, indices, axis, broadcast=True, name=None):
    def f(d, i):
        return jnp.take_along_axis(d, i.astype(jnp.int32), axis=axis)
    return nary(f, [x, ensure_tensor(indices)], name="take_along_axis")


def put_along_axis(x, indices, values, axis, reduce="assign", name=None):
    def f(d, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape) if jnp.ndim(v) else \
            jnp.full(i.shape, v, dtype=d.dtype)
        if reduce == "add":
            return _put_add(d, i, v, axis)
        if reduce in ("mul", "multiply"):
            return _put_mul(d, i, v, axis)
        return jnp.put_along_axis(d, i, v, axis=axis, inplace=False)
    return nary(f, [x, ensure_tensor(indices), ensure_tensor(values)],
                name="put_along_axis")


def _put_add(d, i, v, axis):
    dm = jnp.moveaxis(d, axis, 0)
    im = jnp.moveaxis(i, axis, 0)
    vm = jnp.moveaxis(jnp.broadcast_to(v, i.shape), axis, 0)
    grid = jnp.indices(im.shape)
    idx = (im,) + tuple(grid[k] for k in range(1, im.ndim))
    return jnp.moveaxis(dm.at[idx].add(vm), 0, axis)


def _put_mul(d, i, v, axis):
    dm = jnp.moveaxis(d, axis, 0)
    im = jnp.moveaxis(i, axis, 0)
    vm = jnp.moveaxis(jnp.broadcast_to(v, i.shape), axis, 0)
    grid = jnp.indices(im.shape)
    idx = (im,) + tuple(grid[k] for k in range(1, im.ndim))
    return jnp.moveaxis(dm.at[idx].multiply(vm), 0, axis)


def masked_fill(x, mask, value, name=None):
    # tensor fills (any size, incl. 0-d) stay on device and broadcast in
    # the jnp.where; the old size-1 .item() special case synced per call
    if isinstance(value, Tensor):
        # size-1 fills drop to 0-d (on device) so their rank never
        # broadcasts the output wider than x, matching the scalar path
        return nary(lambda d, m, v: jnp.where(
            m, (v.reshape(()) if v.size == 1 else v).astype(d.dtype), d),
            [x, ensure_tensor(mask), value], name="masked_fill")
    return nary(lambda d, m: jnp.where(m, jnp.asarray(value, dtype=d.dtype), d),
                [x, ensure_tensor(mask)], name="masked_fill")


def masked_select(x, mask, name=None):
    """Dynamic-shaped: eager-only (host sync), like every data-dependent
    shape op on an XLA backend. Inside jit use `where` instead."""
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    if isinstance(x._data, jax.core.Tracer) or isinstance(mask._data, jax.core.Tracer):
        raise RuntimeError(
            "masked_select has a data-dependent output shape and cannot be "
            "traced under jit; use paddle_tpu.where / multiplication by the "
            "mask instead.")
    # eager jnp.nonzero keeps the index computation on device — the
    # data-dependent output shape is why this stays eager-only, but the
    # gather itself never needs a host round-trip
    m = jnp.broadcast_to(mask._data, x._data.shape).ravel()
    (idx,) = jnp.nonzero(m)
    return nary(lambda d: jnp.take(d.ravel(), idx), [x],
                name="masked_select")


def masked_scatter(x, mask, value, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    m = jnp.broadcast_to(mask._data, x._data.shape).ravel()
    (flat_idx,) = jnp.nonzero(m)

    def f(d, v):
        return d.ravel().at[flat_idx].set(
            v.ravel()[:flat_idx.size]).reshape(d.shape)
    return nary(f, [x, ensure_tensor(value)], name="masked_scatter")


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return tuple(Tensor(a) for a in jnp.nonzero(condition._data))
    return nary(lambda c, a, b: jnp.where(c, a, b),
                [condition, x, y], name="where")


def roll(x, shifts, axis=None, name=None):
    sh = _shape_vals(shifts) if isinstance(shifts, (list, tuple, Tensor)) else shifts
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return _unary(lambda d: jnp.roll(d, sh, axis=ax), x, name="roll")


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return _unary(lambda d: jnp.flip(d, axis=ax), x, name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return _unary(lambda d: jnp.rot90(d, k=k, axes=tuple(axes)), x, name="rot90")


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._data)
        total = int(reps.sum())
        return _unary(lambda d: jnp.repeat(d, jnp.asarray(reps), axis=axis,
                                           total_repeat_length=total), x,
                      name="repeat_interleave")
    return _unary(lambda d: jnp.repeat(d, repeats, axis=axis), x,
                  name="repeat_interleave")


def slice(x, axes, starts, ends, name=None):
    x = ensure_tensor(x)
    starts = _shape_vals(starts)
    ends = _shape_vals(ends)
    sls = [np.s_[:]] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        sls[ax] = np.s_[s:e]
    return _unary(lambda d: d[tuple(sls)], x, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)
    sls = [np.s_[:]] * x.ndim
    for ax, s, e, st in zip(axes, _shape_vals(starts), _shape_vals(ends),
                            _shape_vals(strides)):
        sls[ax] = np.s_[s:e:st]
    return _unary(lambda d: d[tuple(sls)], x, name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    shp = _shape_vals(shape)
    offs = _shape_vals(offsets) if offsets is not None else (0,) * x.ndim
    sls = tuple(np.s_[o:o + (s if s != -1 else x.shape[i] - o)]
                for i, (o, s) in enumerate(zip(offs, shp)))
    return _unary(lambda d: d[sls], x, name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    pad = _shape_vals(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full per-dim spec, paddle order = [d0_l, d0_r, d1_l, d1_r, ...]
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec: pair j applies to the (last - j)-th spatial dim
        # (paddle order [left,right, top,bottom, front,back] — W first),
        # honoring data_format (ref F.pad semantics)
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NHWC-style
            spatial_axes = list(range(1, nd - 1))
        else:
            spatial_axes = list(range(2, nd)) if nd > 2 else list(range(nd))
        for j in range(n_spatial):
            axq = spatial_axes[len(spatial_axes) - 1 - j]
            widths[axq] = (pad[2 * j], pad[2 * j + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return _unary(lambda d: jnp.pad(d, widths, mode="constant",
                                        constant_values=value), x, name="pad")
    return _unary(lambda d: jnp.pad(d, widths, mode=jmode), x, name="pad")


def as_complex(x, name=None):
    return _unary(lambda d: jax.lax.complex(d[..., 0], d[..., 1]), x,
                  name="as_complex")


def as_real(x, name=None):
    return _unary(lambda d: jnp.stack([jnp.real(d), jnp.imag(d)], axis=-1),
                  x, name="as_real")


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = axes.numpy().tolist()
    return nary(lambda a, b: jnp.tensordot(a, b, axes=axes), [x, y],
                name="tensordot")


def unflatten(x, axis, shape, name=None):
    x = ensure_tensor(x)
    shp = _shape_vals(shape)
    ax = axis % x.ndim

    def f(d):
        return jnp.reshape(d, d.shape[:ax] + tuple(shp) + d.shape[ax + 1:])
    return _unary(f, x, name="unflatten")


def as_strided(x, shape, stride, offset=0, name=None):
    x = ensure_tensor(x)
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(x._data).ravel()[offset:],
        shape=shape,
        strides=[s * x.element_size() for s in stride])
    return Tensor(jnp.asarray(arr.copy()))


def unfold(x, axis, size, step, name=None):
    x = ensure_tensor(x)
    ax = axis % x.ndim
    n = (x.shape[ax] - size) // step + 1

    def f(d):
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
        g = jnp.take(d, idx.reshape(-1), axis=ax)
        g = jnp.reshape(g, d.shape[:ax] + (n, size) + d.shape[ax + 1:])
        return jnp.moveaxis(g, ax + 1, -1)
    return _unary(f, x, name="unfold")


def numel(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size, dtype=jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(ensure_tensor(x).ndim, dtype=jnp.int32))


def tolist(x):
    return ensure_tensor(x).tolist()


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1, name=None):
    """Map global ids to shard-local ids (ref: ``paddle.shard_index``)."""
    size = (index_num + nshards - 1) // nshards

    def f(d):
        shard = d // size
        local = d % size
        return jnp.where(shard == shard_id, local, ignore_value)
    return _unary(f, x, name="shard_index")


# -- indexing engine for Tensor.__getitem__ / __setitem__ -------------------
def _norm_index(idx):
    """Convert Tensors inside an index expression to arrays."""
    if isinstance(idx, tuple):
        return tuple(_norm_index(i) for i in idx)
    if isinstance(idx, Tensor):
        d = idx._data
        return d if d.dtype == jnp.bool_ else d.astype(jnp.int32)
    if isinstance(idx, (list, np.ndarray)):
        a = np.asarray(idx)
        return a if a.dtype != np.bool_ else a
    return idx


def _has_bool_mask(idx):
    items = idx if isinstance(idx, tuple) else (idx,)
    for i in items:
        if isinstance(i, (jax.Array, np.ndarray)) and i.dtype == np.bool_:
            return True
        if isinstance(i, jax.core.Tracer) and i.dtype == jnp.bool_:
            return True
    return False


def _getitem(x, idx):
    nidx = _norm_index(idx)
    if _has_bool_mask(nidx) and not isinstance(x._data, jax.core.Tracer):
        # dynamic-shape boolean mask: resolve on host (eager only)
        arr = np.asarray(x._data)
        np_idx = jax.tree_util.tree_map(np.asarray, nidx)
        taken = arr[np_idx]
        lin = np.arange(arr.size).reshape(arr.shape)[np_idx]
        return nary(lambda d: jnp.take(d.ravel(),
                                       jnp.asarray(lin.ravel())).reshape(taken.shape),
                    [x], name="getitem_mask")
    return _unary(lambda d: d[nidx], x, name="getitem")


def _setitem(x, idx, value):
    nidx = _norm_index(idx)
    if isinstance(value, Tensor):
        out = nary(lambda d, v: d.at[nidx].set(v.astype(d.dtype)), [x, value],
                   name="setitem")
    else:
        val = jnp.asarray(value) if not np.isscalar(value) else value
        out = nary(lambda d: d.at[nidx].set(val), [x], name="setitem")
    _rebind(x, out)
    return x


def reverse(x, axis, name=None):
    """Reverse along ``axis`` (legacy alias of ``flip``; ref
    ``tensor/manipulation.py reverse``)."""
    return flip(x, axis)


def shape(input, name=None):
    """Shape of ``input`` as an int32 Tensor (ref:
    ``tensor/attribute.py:59``). Shapes are static under XLA, so this is a
    host-side constant — no kernel launch."""
    return Tensor(jnp.asarray(ensure_tensor(input).shape, dtype=jnp.int32))
