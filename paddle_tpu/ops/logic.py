"""Comparison / logical / bitwise ops (ref: ``python/paddle/tensor/logic.py``)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor
from .op_utils import ensure_tensor, unary as _unary, binary as _binary

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_xor", "logical_not", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "bitwise_left_shift", "bitwise_right_shift",
    "is_empty", "is_tensor", "is_complex", "is_integer", "is_floating_point",
]


def equal(x, y, name=None):
    return _binary(jnp.equal, x, y, name="equal")


def not_equal(x, y, name=None):
    return _binary(jnp.not_equal, x, y, name="not_equal")


def greater_than(x, y, name=None):
    return _binary(jnp.greater, x, y, name="greater_than")


def greater_equal(x, y, name=None):
    return _binary(jnp.greater_equal, x, y, name="greater_equal")


def less_than(x, y, name=None):
    return _binary(jnp.less, x, y, name="less_than")


def less_equal(x, y, name=None):
    return _binary(jnp.less_equal, x, y, name="less_equal")


def equal_all(x, y, name=None):
    return _binary(lambda a, b: jnp.array_equal(a, b), x, y, name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _binary(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                             equal_nan=equal_nan),
                   x, y, name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _binary(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                            equal_nan=equal_nan),
                   x, y, name="isclose")


def logical_and(x, y, out=None, name=None):
    return _binary(jnp.logical_and, x, y, name="logical_and")


def logical_or(x, y, out=None, name=None):
    return _binary(jnp.logical_or, x, y, name="logical_or")


def logical_xor(x, y, out=None, name=None):
    return _binary(jnp.logical_xor, x, y, name="logical_xor")


def logical_not(x, out=None, name=None):
    return _unary(jnp.logical_not, x, name="logical_not")


def bitwise_and(x, y, out=None, name=None):
    return _binary(jnp.bitwise_and, x, y, name="bitwise_and")


def bitwise_or(x, y, out=None, name=None):
    return _binary(jnp.bitwise_or, x, y, name="bitwise_or")


def bitwise_xor(x, y, out=None, name=None):
    return _binary(jnp.bitwise_xor, x, y, name="bitwise_xor")


def bitwise_not(x, out=None, name=None):
    return _unary(jnp.bitwise_not, x, name="bitwise_not")


def bitwise_left_shift(x, y, name=None):
    return _binary(jnp.left_shift, x, y, name="bitwise_left_shift")


def bitwise_right_shift(x, y, name=None):
    return _binary(jnp.right_shift, x, y, name="bitwise_right_shift")


def is_empty(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def _kind(x):
    return np.dtype(ensure_tensor(x)._data.dtype).kind


def is_complex(x):
    """ref: ``tensor/attribute.py is_complex`` — host-side dtype predicate."""
    return _kind(x) == "c"


def is_integer(x):
    return _kind(x) in "iu"


def is_floating_point(x):
    d = ensure_tensor(x)._data.dtype
    return _kind(x) == "f" or d == jnp.bfloat16
