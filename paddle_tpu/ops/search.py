"""Search / sort ops (ref: ``python/paddle/tensor/search.py``).

Sorts and top-k lower to XLA's sort HLO; `unique`/`nonzero` have
data-dependent shapes and are eager-only (the same ops are GPU-sync points in
the reference too).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..framework.dtype import to_jax_dtype
from .op_utils import ensure_tensor, unary as _unary, nary

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
    "nonzero", "searchsorted", "bucketize", "index_select", "masked_select",
    "unique", "unique_consecutive", "histogram", "histogramdd", "bincount",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = to_jax_dtype(dtype)

    def f(d):
        out = jnp.argmax(d.ravel() if axis is None else d,
                         axis=None if axis is None else axis,
                         keepdims=keepdim if axis is not None else False)
        return out.astype(dt)
    return _unary(f, x, name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = to_jax_dtype(dtype)

    def f(d):
        out = jnp.argmin(d.ravel() if axis is None else d,
                         axis=None if axis is None else axis,
                         keepdims=keepdim if axis is not None else False)
        return out.astype(dt)
    return _unary(f, x, name="argmin")


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    def f(d):
        idx = jnp.argsort(d, axis=axis, stable=stable,
                          descending=descending)
        return idx.astype(jnp.int32)
    return _unary(f, x, name="argsort")


def sort(x, axis=-1, descending=False, stable=True, name=None):
    def f(d):
        return jnp.sort(d, axis=axis, stable=stable, descending=descending)
    return _unary(f, x, name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)
    ax = (axis if axis is not None else -1) % x.ndim

    def f(d):
        dm = jnp.moveaxis(d, ax, -1)
        if largest:
            v, i = jax.lax.top_k(dm, kk)
        else:
            v, i = jax.lax.top_k(-dm, kk)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i.astype(jnp.int64 if False else jnp.int32), -1, ax)
    return nary(f, [x], name="topk", n_out=2)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis % x.ndim

    def f(d):
        s = jnp.sort(d, axis=ax)
        si = jnp.argsort(d, axis=ax)
        v = jnp.take(s, k - 1, axis=ax)
        i = jnp.take(si, k - 1, axis=ax)
        if keepdim:
            v, i = jnp.expand_dims(v, ax), jnp.expand_dims(i, ax)
        return v, i.astype(jnp.int32)
    return nary(f, [x], name="kthvalue", n_out=2)


def mode(x, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = axis % x.ndim

    def f(d):
        s = jnp.sort(d, axis=ax)
        # longest run of equal values along axis
        dm = jnp.moveaxis(s, ax, -1)
        n = dm.shape[-1]
        eq = dm[..., :, None] == dm[..., None, :]
        counts = eq.sum(-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(dm, best[..., None], axis=-1)[..., 0]
        # index of last occurrence in original order
        orig = jnp.moveaxis(d, ax, -1)
        match = (orig == vals[..., None]).astype(jnp.int32)
        idx = jnp.argmax(match * (jnp.arange(n) + 1), axis=-1)
        if keepdim:
            vals, idx = vals[..., None], idx[..., None]
            return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int32), -1, ax)
        return vals, idx.astype(jnp.int32)
    return nary(f, [x], name="mode", n_out=2)


def nonzero(x, as_tuple=False):
    x = ensure_tensor(x)
    if isinstance(x._data, jax.core.Tracer):
        raise RuntimeError("nonzero has data-dependent shape; eager only")
    idx = jnp.nonzero(x._data)  # eager: on-device, no host round-trip
    if as_tuple:
        return tuple(Tensor(i) for i in idx)
    return Tensor(jnp.stack(idx, axis=1))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    dt = jnp.int32

    def f(s, v):
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side).astype(dt)
        return jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(
            s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape).astype(dt)
    return nary(f, [ensure_tensor(sorted_sequence), ensure_tensor(values)],
                name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_select(x, index, axis=0, name=None):
    from .manipulation import index_select as _is
    return _is(x, index, axis)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms
    return _ms(x, mask)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    if isinstance(x._data, jax.core.Tracer):
        raise RuntimeError("unique has data-dependent shape; eager only "
                           "(use jnp.unique with size= inside jit)")
    arr = np.asarray(x._data)
    out = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(out))
    outs = [Tensor(jnp.asarray(o)) for o in out]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.ravel()
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
    else:
        diff = (arr.take(range(1, arr.shape[axis]), axis=axis) !=
                arr.take(range(arr.shape[axis] - 1), axis=axis))
        keep = np.concatenate([[True], diff.any(
            axis=tuple(i for i in range(arr.ndim) if i != axis))])
    vals = arr[keep] if axis is None else arr.compress(keep, axis=axis)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        pos = np.nonzero(keep)[0]
        counts = np.diff(np.append(pos, keep.size))
        outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def histogram(x, bins=100, min=0, max=0, weight=None, density=False, name=None):
    x = ensure_tensor(x)

    def f(d, *w):
        lo, hi = (min, max) if (min != 0 or max != 0) else \
            (d.min(), d.max())
        h, _ = jnp.histogram(d.ravel(), bins=bins, range=(lo, hi),
                             weights=w[0].ravel() if w else None,
                             density=density)
        return h if (density or w) else h.astype(jnp.int32)
    args = [x] + ([ensure_tensor(weight)] if weight is not None else [])
    return nary(f, args, name="histogram")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    x = ensure_tensor(x)
    h, edges = np.histogramdd(np.asarray(x._data), bins=bins, range=ranges,
                              density=density,
                              weights=np.asarray(weights._data) if weights is not None else None)
    return Tensor(jnp.asarray(h)), [Tensor(jnp.asarray(e)) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    n = int(np.asarray(x._data).max()) + 1 if x.size else 0
    length = builtins_max(n, minlength)

    def f(d, *w):
        return jnp.bincount(d.ravel().astype(jnp.int32),
                            weights=w[0].ravel() if w else None,
                            length=length)
    args = [x] + ([ensure_tensor(weights)] if weights is not None else [])
    return nary(f, args, name="bincount")


def builtins_max(a, b):
    return a if a > b else b
