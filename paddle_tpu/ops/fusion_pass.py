"""Jaxpr pattern-matching fusion pass: megakernels across op boundaries.

PR 8's kernels fuse within one op; this pass (the FlashFuser direction
from PAPERS.md) walks a whole captured step's jaxpr and rewrites
eligible multi-op subgraphs to the block-fused Pallas kernels in
:mod:`.fused_kernels` — so ``nn.LayerNorm``-heavy models get megakernels
with zero source changes.  Patterns matched:

=================== =======================================================
``layer_norm``       the XLA layernorm soup (mean / ``_var`` pjit / rsqrt /
                     affine) → :func:`fused_kernels.fused_layer_norm`
``residual_ln``      residual add feeding that soup, add consumed only by
                     it (post-LN transformers) → fused residual+LN kernel
``ln_matmul``        the soup's output feeding a single matmul (+bias)
                     (pre-LN qkv/mlp projections) →
                     :func:`fused_kernels.fused_ln_matmul`
``matmul_bias_gelu`` matmul + bias + gelu (tanh or erf form) →
                     :func:`fused_kernels.fused_matmul_bias_gelu`
``attention_block``  qk-matmul + scale (+ causal mask) + softmax +
                     pv-matmul → :func:`fused_kernels.fused_attention_block`
=================== =======================================================

Eligibility is structural: a subgraph is rewritten only when every
interior value is consumed inside the cluster (the cluster is *closed*
except for its single output).  Captured step jaxprs are post-AD — the
tape's backward re-traces the forward per-op, so forward clusters are
closed and replaceable while the backward's recompute copy (whose
interiors feed transposes) is left alone.

Dispatch is canary-probed per pattern, resolved once per process: on a
real TPU the cluster call runs the Pallas kernel; otherwise it runs an
inline XLA reference that mirrors the matched soup (reason
``tpu_unreachable`` — CPU timing and parity are unchanged, interpret
mode is never on the rewritten path).  ``PT_FUSION_PASS=0`` kills the
pass; ``PT_FUSION_DISABLE=pat1,pat2`` opts out individual patterns.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import core as jcore

__all__ = [
    "PATTERNS", "wrap", "match_jaxpr", "match_report", "count_patterns",
    "fusion_enabled", "disabled_patterns", "summary", "reset_stats",
]

PATTERNS = ("attention_block", "matmul_bias_gelu", "ln_matmul",
            "residual_ln", "layer_norm")

_FALSY = {"0", "false", "no", "off"}

_SQRT_HALF = 0.7071067811865476
_TANH_COEF = 0.7978845608028654
_TANH_CUBIC = 0.044715


def fusion_enabled() -> bool:
    return os.environ.get(
        "PT_FUSION_PASS", "1").strip().lower() not in _FALSY


def disabled_patterns() -> set:
    raw = os.environ.get("PT_FUSION_DISABLE", "")
    return {t.strip() for t in raw.split(",") if t.strip()}


# ---------------------------------------------------------------------------
# stats + telemetry
# ---------------------------------------------------------------------------
_stats = {"rewrites": {}, "fallbacks": {}, "traces": 0}


def reset_stats():
    _stats["rewrites"] = {}
    _stats["fallbacks"] = {}
    _stats["traces"] = 0


def summary():
    """Per-process pass stats for bench/capture records: pattern →
    rewrite count, ``pattern:reason`` → fallback count, traces seen."""
    return {"rewrites": dict(_stats["rewrites"]),
            "fallbacks": dict(_stats["fallbacks"]),
            "traces": _stats["traces"]}


def _note_rewrite(pattern):
    _stats["rewrites"][pattern] = _stats["rewrites"].get(pattern, 0) + 1
    try:
        from ..observability.telemetry import get_telemetry
        get_telemetry().fusion_rewrite(pattern)
    except Exception:
        pass


def _note_fallback(pattern, reason):
    key = f"{pattern}:{reason}"
    _stats["fallbacks"][key] = _stats["fallbacks"].get(key, 0) + 1
    try:
        from ..observability.telemetry import get_telemetry
        get_telemetry().fusion_fallback(pattern, reason)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# canary-probed backend resolution (per pattern, cached per process)
# ---------------------------------------------------------------------------
_BACKEND_CACHE: dict = {}


def _reset_dispatch_cache():
    _BACKEND_CACHE.clear()


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _canary(pattern):
    """Run the pattern's fused kernel on a tiny probe eagerly; any
    exception disqualifies the Pallas route for this process."""
    from . import fused_kernels as fk
    x = jnp.zeros((8, 128), jnp.float32)
    if pattern in ("layer_norm", "residual_ln"):
        out = fk.fused_layer_norm(x, residual=x, interpret=False)
    elif pattern == "ln_matmul":
        out = fk.fused_ln_matmul(x, jnp.zeros((128, 128), jnp.float32),
                                 interpret=False)
    elif pattern == "matmul_bias_gelu":
        out = fk.fused_matmul_bias_gelu(
            x, jnp.zeros((128, 128), jnp.float32), interpret=False)
    elif pattern == "attention_block":
        q = jnp.zeros((1, 1, 128, 64), jnp.float32)
        out = fk.fused_attention_block(q, q, q, causal=True,
                                       interpret=False)
    else:
        raise ValueError(pattern)
    # one-shot offline self-test of a compiled kernel, not a step
    # loop — the sync is the point
    # tpu-lint: disable=TPU017
    return bool(jnp.all(jnp.isfinite(out)))


def _backend(pattern):
    """``("pallas", None)`` or ``("xla", reason)`` for a pattern —
    resolved eagerly the first time a cluster of that pattern is
    rewritten, then cached (trace-safe: probes run on concrete zeros)."""
    hit = _BACKEND_CACHE.get(pattern)
    if hit is not None:
        return hit
    if not _on_tpu():
        resolved = ("xla", "tpu_unreachable")
    else:
        try:
            resolved = ("pallas", None) if _canary(pattern) \
                else ("xla", "canary_failed")
        except Exception:
            resolved = ("xla", "canary_failed")
    _BACKEND_CACHE[pattern] = resolved
    return resolved


# ---------------------------------------------------------------------------
# jaxpr graph view + matching helpers
# ---------------------------------------------------------------------------
_OUT = -1          # consumer sentinel for jaxpr outvars


def _is_lit(v):
    return isinstance(v, jcore.Literal)


def _scalar_lit(v):
    """Python float of a rank-0 Literal, else None."""
    if not _is_lit(v):
        return None
    try:
        import numpy as np
        if np.ndim(v.val) != 0:
            return None
        return float(v.val)
    except Exception:
        return None


def _split_lit(eqn):
    """(var, scalar) for a binary eqn with exactly one scalar-literal
    operand, else (None, None)."""
    a, b = eqn.invars
    la, lb = _scalar_lit(a), _scalar_lit(b)
    if la is None and lb is not None:
        return a, lb
    if lb is None and la is not None:
        return b, la
    return None, None


def _coef_close(val, ref):
    """Coefficient-literal compare tolerant of reduced-precision
    literals: a bf16 graph stores sqrt(2/pi) as 0.796875."""
    return val is not None and abs(val - ref) <= 0.01 * abs(ref)


def _conv_src(g, v):
    """Follow one ``convert_element_type`` producer of ``v``: (source
    var, convert eqn idx), or ``(v, None)`` when ``v`` is not a cast.
    AMP graphs re-emit a separate cast per ``.astype`` call site, so
    identity checks go through this to reach the shared source."""
    ci = g.pe(v, "convert_element_type")
    if ci is None:
        return v, None
    s = g.eqns[ci].invars[0]
    if _is_lit(s):
        return v, None
    return s, ci


class _Graph:
    def __init__(self, jaxpr):
        self.eqns = list(jaxpr.eqns)
        self.producer_idx = {}
        self.consumers = {}
        for i, e in enumerate(self.eqns):
            for v in e.outvars:
                self.producer_idx[v] = i
            for v in e.invars:
                if not _is_lit(v):
                    self.consumers.setdefault(v, []).append(i)
        for v in jaxpr.outvars:
            if not _is_lit(v):
                self.consumers.setdefault(v, []).append(_OUT)

    def producer(self, v):
        if _is_lit(v):
            return None
        return self.producer_idx.get(v)

    def pe(self, v, prim):
        """Producing eqn of ``v`` if its primitive is ``prim``."""
        i = self.producer(v)
        if i is None or self.eqns[i].primitive.name != prim:
            return None
        return i

    def sole_consumer(self, v, prim=None):
        cons = self.consumers.get(v, [])
        if len(cons) != 1 or cons[0] == _OUT:
            return None
        if prim is not None and \
                self.eqns[cons[0]].primitive.name != prim:
            return None
        return cons[0]


class Cluster:
    """One matched, rewritable subgraph."""
    __slots__ = ("pattern", "covered", "root", "invars", "outvar", "meta")

    def __init__(self, pattern, covered, invars, outvar, meta):
        self.pattern = pattern
        self.covered = frozenset(covered)
        self.root = max(covered)
        self.invars = list(invars)
        self.outvar = outvar
        self.meta = dict(meta)


def _closed(g, covered, outvar):
    """True when no interior value of the cluster escapes: every outvar
    of a covered eqn (except the cluster output) is consumed only by
    covered eqns — the structural eligibility test."""
    for i in covered:
        if g.eqns[i].effects:
            return False
        for ov in g.eqns[i].outvars:
            if ov is outvar:
                continue
            for ci in g.consumers.get(ov, []):
                if ci == _OUT or ci not in covered:
                    return False
    return True


def _absorb_bias_vec(g, eqn, val_var):
    """For ``add(val, broadcast_in_dim(b))`` (either order) with 1-D
    ``b`` whose broadcast is solely consumed here: (b_var, bcast_idx),
    else (None, None)."""
    for a, other in ((eqn.invars[0], eqn.invars[1]),
                     (eqn.invars[1], eqn.invars[0])):
        if a is not val_var or _is_lit(other):
            continue
        bi = g.pe(other, "broadcast_in_dim")
        if bi is None:
            continue
        src = g.eqns[bi].invars[0]
        if _is_lit(src) or src.aval.ndim != 1:
            continue
        if g.sole_consumer(g.eqns[bi].outvars[0]) is None:
            continue
        return src, bi
    return None, None


def _simple_dot(eqn, lhs_ndim):
    """True for an unbatched last-dim × dim-0 matmul with 2-D rhs."""
    if eqn.primitive.name != "dot_general":
        return False
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    return (tuple(lc), tuple(rc)) == ((lhs_ndim - 1,), (0,)) and \
        not lb and not rb and eqn.invars[1].aval.ndim == 2


# ---------------------------------------------------------------------------
# matcher: layer_norm / residual_ln / ln_matmul
# ---------------------------------------------------------------------------
def _match_ln(g, ri, claimed):
    eqns = g.eqns
    if eqns[ri].primitive.name != "rsqrt":
        return None
    ai = g.producer(eqns[ri].invars[0])
    if ai is None or eqns[ai].primitive.name != "add":
        return None
    var_v, eps = _split_lit(eqns[ai])
    if var_v is None:
        return None
    vi = g.pe(var_v, "pjit")
    if vi is None or eqns[vi].params.get("name") != "_var":
        return None
    # jnp.var(x, ddof): second operand must be the ddof literal 0
    ddof = _scalar_lit(eqns[vi].invars[1]) \
        if len(eqns[vi].invars) > 1 else 0.0
    if ddof != 0.0:
        return None
    x = eqns[vi].invars[0]
    if _is_lit(x) or x.aval.ndim < 2:
        return None
    d = x.aval.shape[-1]
    # AMP models widen the soup with a cast per .astype call site; the
    # three stats reads then see three distinct convert outputs of one
    # shared source — identity checks go through the source var
    x_src, cva = _conv_src(g, x)
    stats_dtype = x.aval.dtype

    def same_as_x(v):
        """v is the stats input, or another cast of its source to the
        same stats dtype: (True, convert idx|None)."""
        if v is x:
            return True, None
        s, ci = _conv_src(g, v)
        if ci is not None and s is x_src and \
                v.aval.dtype == stats_dtype:
            return True, ci
        return False, None

    # (x - mean) * rstd, with mean = div(bcast(reduce_sum(x)), d)
    mi = g.sole_consumer(eqns[ri].outvars[0], "mul")
    if mi is None:
        return None
    sub_v = eqns[mi].invars[0] if eqns[mi].invars[1] is \
        eqns[ri].outvars[0] else eqns[mi].invars[1]
    si = g.pe(sub_v, "sub")
    if si is None:
        return None
    ok, c_sub = same_as_x(eqns[si].invars[0])
    if not ok:
        return None
    mean_v = eqns[si].invars[1]
    di = g.pe(mean_v, "div")
    if di is None or _scalar_lit(eqns[di].invars[1]) != float(d):
        return None
    bi = g.pe(eqns[di].invars[0], "broadcast_in_dim")
    if bi is None:
        return None
    rsi = g.pe(eqns[bi].invars[0], "reduce_sum")
    if rsi is None or \
            tuple(eqns[rsi].params["axes"]) != (x.aval.ndim - 1,):
        return None
    ok, c_mean = same_as_x(eqns[rsi].invars[0])
    if not ok:
        return None

    covered = {rsi, bi, di, vi, si, ai, ri, mi}
    for ci in (cva, c_sub, c_mean):
        if ci is not None:
            covered.add(ci)
    y = eqns[mi].outvars[0]
    w_var = b_var = None

    # optional cast between normalization and affine (AMP: stats run in
    # f32, the affine in the model dtype)
    ci0 = g.sole_consumer(y, "convert_element_type")
    if ci0 is not None:
        covered.add(ci0)
        y = eqns[ci0].outvars[0]
    affine_dtype = y.aval.dtype

    # optional affine: * broadcast(w) then + broadcast(b)
    wi = g.sole_consumer(y, "mul")
    if wi is not None:
        wv, wbi = _absorb_bias_vec(g, eqns[wi], y)
        if wv is not None and wv.aval.shape == (d,):
            w_var = wv
            covered |= {wi, wbi}
            y = eqns[wi].outvars[0]
    bi2 = g.sole_consumer(y, "add")
    if bi2 is not None:
        bv, bbi = _absorb_bias_vec(g, eqns[bi2], y)
        if bv is not None and bv.aval.shape == (d,):
            b_var = bv
            covered |= {bi2, bbi}
            y = eqns[bi2].outvars[0]

    # optional trailing convert (bf16 models cast the f32 soup back)
    ci = g.sole_consumer(y, "convert_element_type")
    if ci is not None:
        covered.add(ci)
        y = eqns[ci].outvars[0]
    ln_dtype = y.aval.dtype

    # optional residual: absorb the producing add when the sum is
    # consumed only inside the cluster (post-LN blocks; a pre-LN
    # residual also feeds the next block's add and stays outside)
    res_in = None
    pi = g.producer(x_src)
    if pi is not None and eqns[pi].primitive.name == "add" and \
            not any(_is_lit(v) for v in eqns[pi].invars) and \
            eqns[pi].invars[0].aval.shape == x_src.aval.shape and \
            eqns[pi].invars[1].aval.shape == x_src.aval.shape and \
            set(g.consumers.get(x_src, [])) <= covered:
        covered.add(pi)
        res_in = (eqns[pi].invars[0], eqns[pi].invars[1])

    # optional matmul epilogue: LN output as the lhs of one plain matmul
    mw_var = mb_var = None
    pref = None
    dmi = g.sole_consumer(y, "dot_general")
    if dmi is not None and dmi not in claimed and \
            _simple_dot(eqns[dmi], y.aval.ndim) and \
            eqns[dmi].invars[0] is y and \
            not _is_lit(eqns[dmi].invars[1]):
        mw_var = eqns[dmi].invars[1]
        pref = eqns[dmi].params.get("preferred_element_type")
        covered.add(dmi)
        y = eqns[dmi].outvars[0]
        abi = g.sole_consumer(y, "add")
        if abi is not None and abi not in claimed:
            bv, bbi = _absorb_bias_vec(g, eqns[abi], y)
            if bv is not None:
                mb_var = bv
                covered |= {abi, bbi}
                y = eqns[abi].outvars[0]

    if mw_var is not None:
        pattern = "ln_matmul"
    elif res_in is not None:
        pattern = "residual_ln"
    else:
        pattern = "layer_norm"

    invars = list(res_in) if res_in is not None else [x_src]
    meta = {"eps": float(eps), "res": res_in is not None,
            "w": w_var is not None, "b": b_var is not None,
            "matmul": mw_var is not None, "mbias": mb_var is not None,
            "pref": pref, "ln_dtype": ln_dtype,
            "stats_dtype": stats_dtype, "affine_dtype": affine_dtype,
            "out_dtype": y.aval.dtype}
    for v in (w_var, b_var, mw_var, mb_var):
        if v is not None:
            invars.append(v)
    return Cluster(pattern, covered, invars, y, meta)


# ---------------------------------------------------------------------------
# matcher: matmul + bias + gelu (tanh and erf lowerings)
# ---------------------------------------------------------------------------
def _match_mbg_pre(g, z):
    """Locate the matmul (+ bias) producing the gelu argument ``z``:
    (covered, x, w, b, pref) or None."""
    eqns = g.eqns
    b_var = None
    covered = set()
    di = g.producer(z)
    if di is None:
        return None
    if eqns[di].primitive.name == "add":
        a, b = eqns[di].invars
        dot_v = a if g.pe(a, "dot_general") is not None else b
        bv, bbi = _absorb_bias_vec(g, eqns[di], dot_v)
        if bv is None:
            return None
        b_var = bv
        covered |= {di, bbi}
        di = g.pe(dot_v, "dot_general")
        if di is None:
            return None
    if eqns[di].primitive.name != "dot_general":
        return None
    x = eqns[di].invars[0]
    if _is_lit(x) or not _simple_dot(eqns[di], x.aval.ndim):
        return None
    covered.add(di)
    return covered, x, eqns[di].invars[1], b_var, \
        eqns[di].params.get("preferred_element_type")


def _match_mbg_tanh(g, ti):
    eqns = g.eqns
    if eqns[ti].primitive.name != "tanh":
        return None
    ji = g.producer(eqns[ti].invars[0])
    if ji is None or eqns[ji].primitive.name != "mul":
        return None
    inner_v, coef = _split_lit(eqns[ji])
    if inner_v is None or not _coef_close(coef, _TANH_COEF):
        return None
    ii = g.pe(inner_v, "add")
    if ii is None:
        return None
    # add(z, mul(0.044715, z**3)) — z on either side
    z = cub = None
    for a, b in ((eqns[ii].invars[0], eqns[ii].invars[1]),
                 (eqns[ii].invars[1], eqns[ii].invars[0])):
        hi = g.pe(b, "mul")
        if hi is None:
            continue
        gv, c3 = _split_lit(eqns[hi])
        if gv is None or not _coef_close(c3, _TANH_CUBIC):
            continue
        pi = g.pe(gv, "integer_pow")
        if pi is None or eqns[pi].params.get("y") != 3 or \
                eqns[pi].invars[0] is not a:
            continue
        z, cub = a, (hi, pi)
        break
    if z is None:
        return None
    li = g.sole_consumer(eqns[ti].outvars[0], "add")
    if li is None:
        return None
    lv, one = _split_lit(eqns[li])
    if lv is None or one != 1.0:
        return None
    mi = g.sole_consumer(eqns[li].outvars[0], "mul")
    if mi is None:
        return None
    mv, half = _split_lit(eqns[mi])
    if mv is None or half != 0.5:
        return None
    ni = g.sole_consumer(eqns[mi].outvars[0], "mul")
    if ni is None or z not in eqns[ni].invars:
        return None
    pre = _match_mbg_pre(g, z)
    if pre is None:
        return None
    covered, x, w, b, pref = pre
    covered |= {ji, ii, cub[0], cub[1], ti, li, mi, ni}
    y = eqns[ni].outvars[0]
    invars = [x, w] + ([b] if b is not None else [])
    return Cluster("matmul_bias_gelu", covered, invars, y,
                   {"approximate": True, "bias": b is not None,
                    "pref": pref, "out_dtype": y.aval.dtype})


def _match_mbg_erf(g, ei):
    eqns = g.eqns
    if eqns[ei].primitive.name != "erfc":
        return None
    mi = g.producer(eqns[ei].invars[0])
    if mi is None or eqns[mi].primitive.name != "mul":
        return None
    neg_v, coef = _split_lit(eqns[mi])
    if neg_v is None or not _coef_close(coef, _SQRT_HALF):
        return None
    ci = g.pe(neg_v, "neg")
    if ci is None:
        return None
    z = eqns[ci].invars[0]
    fi = g.sole_consumer(eqns[ei].outvars[0], "mul")
    if fi is None:
        return None
    half_v = eqns[fi].invars[0] if eqns[fi].invars[1] is \
        eqns[ei].outvars[0] else eqns[fi].invars[1]
    hi = g.pe(half_v, "mul")
    if hi is None:
        return None
    zv, half = _split_lit(eqns[hi])
    if zv is not z or half != 0.5:
        return None
    covered = {mi, ci, ei, fi, hi}
    y = eqns[fi].outvars[0]
    cpi = g.sole_consumer(y, "copy")
    if cpi is not None:
        covered.add(cpi)
        y = eqns[cpi].outvars[0]
    pre = _match_mbg_pre(g, z)
    if pre is None:
        return None
    pcov, x, w, b, pref = pre
    covered |= pcov
    invars = [x, w] + ([b] if b is not None else [])
    return Cluster("matmul_bias_gelu", covered, invars, y,
                   {"approximate": False, "bias": b is not None,
                    "pref": pref, "out_dtype": y.aval.dtype})


# ---------------------------------------------------------------------------
# matcher: attention block (qk matmul + scale + softmax + pv matmul)
# ---------------------------------------------------------------------------
_QK_DIMS = (((3,), (3,)), ((0, 1), (0, 1)))
_PV_DIMS = (((3,), (2,)), ((0, 1), (0, 1)))


def _dot_dims(eqn):
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    return ((tuple(lc), tuple(rc)), (tuple(lb), tuple(rb)))


def _match_attention(g, pi):
    eqns = g.eqns
    if eqns[pi].primitive.name != "dot_general" or \
            eqns[pi].invars[0].aval.ndim != 4 or \
            _dot_dims(eqns[pi]) != _PV_DIMS:
        return None
    p, v = eqns[pi].invars
    if _is_lit(p) or _is_lit(v):
        return None
    p_dtype = p.aval.dtype
    pv_pref = eqns[pi].params.get("preferred_element_type")
    # AMP casts the f32 softmax island back to the model dtype before
    # the pv matmul — step through the cast
    p_src, c_p = _conv_src(g, p)
    # softmax chain: div(exp, bcast(reduce_sum(exp)))
    dvi = g.pe(p_src, "div")
    if dvi is None:
        return None
    exp_v, den_v = eqns[dvi].invars
    xpi = g.pe(exp_v, "exp")
    bgi = g.pe(den_v, "broadcast_in_dim")
    if xpi is None or bgi is None:
        return None
    rsi = g.pe(eqns[bgi].invars[0], "reduce_sum")
    if rsi is None or eqns[rsi].invars[0] is not exp_v:
        return None
    sbi = g.pe(eqns[xpi].invars[0], "sub")
    if sbi is None:
        return None
    scores, max_b = eqns[sbi].invars
    sgi = g.pe(max_b, "stop_gradient")
    if sgi is None:
        return None
    bbi = g.pe(eqns[sgi].invars[0], "broadcast_in_dim")
    if bbi is None:
        return None
    mxi = g.pe(eqns[bbi].invars[0], "max")
    if mxi is None:
        return None
    rm_v, _ninf = _split_lit(eqns[mxi])
    rmi = g.pe(rm_v, "reduce_max") if rm_v is not None else None
    if rmi is None or eqns[rmi].invars[0] is not scores:
        return None
    covered = {pi, dvi, xpi, bgi, rsi, sbi, sgi, bbi, mxi, rmi}
    if c_p is not None:
        covered.add(c_p)
    s_dtype = eqns[xpi].outvars[0].aval.dtype

    # causal mask: scores = _where(tril(...), scaled, -inf)
    causal = False
    wi = g.producer(scores)
    if wi is not None and eqns[wi].primitive.name == "pjit" and \
            eqns[wi].params.get("name") == "_where":
        tri = g.pe(eqns[wi].invars[0], "pjit")
        if tri is None or eqns[tri].params.get("name") != "tril":
            return None
        covered |= {wi, tri}
        ti = g.pe(eqns[tri].invars[0], "broadcast_in_dim")
        if ti is not None:
            covered.add(ti)
        causal = True
        scores = eqns[wi].invars[1]

    # scale: mul(qk, sm_scale) — optional (sm_scale == 1 emits no mul);
    # AMP interposes a cast between the bf16 qk matmul and the f32 scale
    sm_scale = 1.0
    sci = g.producer(scores)
    if sci is not None and eqns[sci].primitive.name == "mul":
        qk_v, sc = _split_lit(eqns[sci])
        if qk_v is not None and \
                g.pe(_conv_src(g, qk_v)[0], "dot_general") is not None:
            sm_scale = float(sc)
            covered.add(sci)
            scores = qk_v
    scores, c_qk = _conv_src(g, scores)
    if c_qk is not None:
        covered.add(c_qk)
    sci = g.producer(scores)
    if sci is None or eqns[sci].primitive.name != "dot_general" or \
            _dot_dims(eqns[sci]) != _QK_DIMS:
        return None
    q, k = eqns[sci].invars
    if _is_lit(q) or _is_lit(k):
        return None
    covered.add(sci)
    y = eqns[pi].outvars[0]
    return Cluster("attention_block", covered, [q, k, v], y,
                   {"causal": causal, "sm_scale": sm_scale,
                    "qk_pref": eqns[sci].params.get(
                        "preferred_element_type"),
                    "pv_pref": pv_pref, "s_dtype": s_dtype,
                    "p_dtype": p_dtype, "out_dtype": y.aval.dtype})


# ---------------------------------------------------------------------------
# pass driver
# ---------------------------------------------------------------------------
def match_jaxpr(jaxpr, disabled=None):
    """Match all rewritable clusters in ``jaxpr``, highest-priority
    pattern first (attention → gelu → LN family, so e.g. an MLP fc1 dot
    is claimed by the gelu cluster and the preceding LN falls back to a
    bare layer_norm).  Returns non-overlapping, closure-checked
    :class:`Cluster` objects in program order."""
    if disabled is None:
        disabled = disabled_patterns()
    g = _Graph(jaxpr)
    clusters, claimed = [], set()

    def take(cl):
        if cl is None or cl.pattern in disabled:
            return
        if cl.covered & claimed:
            return
        if not _closed(g, cl.covered, cl.outvar):
            return
        claimed.update(cl.covered)
        clusters.append(cl)

    for i in range(len(g.eqns)):
        take(_match_attention(g, i))
    for i in range(len(g.eqns)):
        take(_match_mbg_tanh(g, i))
        take(_match_mbg_erf(g, i))
    for i in range(len(g.eqns)):
        take(_match_ln(g, i, claimed))
    clusters.sort(key=lambda c: c.root)
    return clusters


def count_patterns(fn, *args, **kwargs):
    """Pattern → match count for ``fn(*args)`` without executing it —
    the bench/tests introspection entry."""
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    counts = {}
    for cl in match_jaxpr(closed.jaxpr):
        counts[cl.pattern] = counts.get(cl.pattern, 0) + 1
    return counts


def match_report(jaxpr, disabled=None):
    """Eligibility census for the graph auditor (``tools/audit``):
    like :func:`match_jaxpr`, but additionally keeps the structural
    matches that FAILED the closure test, each with a string naming the
    first blocking escape.

    Returns ``(clusters, near_misses)``: the eligible clusters exactly
    as :func:`match_jaxpr` would pick them, plus a list of
    ``(cluster, blocker)`` pairs where ``blocker`` names the interior
    value and the outside consumer that pins it (the jaxpr output, a
    foreign eqn, or an effectful member eqn)."""
    if disabled is None:
        disabled = disabled_patterns()
    g = _Graph(jaxpr)
    clusters, near, claimed, near_claimed = [], [], set(), set()

    def _blocker(cl):
        for i in sorted(cl.covered):
            eqn = g.eqns[i]
            if eqn.effects:
                return f"member eqn {eqn.primitive.name} carries effects"
            for ov in eqn.outvars:
                if ov is cl.outvar:
                    continue
                for ci in g.consumers.get(ov, []):
                    if ci == _OUT:
                        return (f"interior {eqn.primitive.name} result "
                                f"{ov.aval.str_short()} escapes to the "
                                "program output")
                    if ci not in cl.covered:
                        return (f"interior {eqn.primitive.name} result "
                                f"{ov.aval.str_short()} escapes to eqn "
                                f"{g.eqns[ci].primitive.name}")
        return None

    def take(cl):
        if cl is None or cl.pattern in disabled:
            return
        if cl.covered & claimed:
            return
        b = _blocker(cl)
        if b is not None:
            if not (cl.covered & near_claimed):
                near_claimed.update(cl.covered)
                near.append((cl, b))
            return
        claimed.update(cl.covered)
        clusters.append(cl)

    for i in range(len(g.eqns)):
        take(_match_attention(g, i))
    for i in range(len(g.eqns)):
        take(_match_mbg_tanh(g, i))
        take(_match_mbg_erf(g, i))
    for i in range(len(g.eqns)):
        take(_match_ln(g, i, claimed))
    clusters.sort(key=lambda c: c.root)
    near.sort(key=lambda nb: nb[0].root)
    return clusters, near


def _bvec(v, ndim):
    return jnp.reshape(v, (1,) * (ndim - 1) + (v.shape[-1],))


def _cluster_fn(cl):
    """Build the callable replacing cluster ``cl``: Pallas block kernel
    on TPU, inline XLA mirror of the matched soup otherwise."""
    pattern, meta = cl.pattern, cl.meta
    backend, reason = _backend(pattern)
    if backend != "pallas":
        _note_fallback(pattern, reason)
    from . import fused_kernels as fk

    if pattern == "attention_block":
        causal, scale = meta["causal"], meta["sm_scale"]

        def call(q, k, v):
            if backend == "pallas":
                out = fk.fused_attention_block(
                    q, k, v, causal=causal, sm_scale=scale,
                    interpret=False)
            else:
                s = jax.lax.dot_general(
                    q, k, dimension_numbers=_QK_DIMS,
                    preferred_element_type=meta.get("qk_pref"))
                s = s.astype(meta.get("s_dtype", s.dtype)) * scale
                if causal:
                    mask = jnp.tril(jnp.ones(
                        (q.shape[2], k.shape[2]), bool))
                    s = jnp.where(mask, s, -jnp.inf)
                p = jax.nn.softmax(s, axis=-1)
                p = p.astype(meta.get("p_dtype", p.dtype))
                out = jax.lax.dot_general(
                    p, v, dimension_numbers=_PV_DIMS,
                    preferred_element_type=meta.get("pv_pref"))
            return out.astype(meta["out_dtype"])
        return call

    if pattern == "matmul_bias_gelu":
        approx, pref = meta["approximate"], meta["pref"]

        def call(x, w, b=None):
            if backend == "pallas":
                rows = 1
                for s in x.shape[:-1]:
                    rows *= s
                y = fk.fused_matmul_bias_gelu(
                    x.reshape(rows, x.shape[-1]), w, b,
                    approximate=approx, interpret=False)
                out = y.reshape(x.shape[:-1] + (w.shape[1],))
            else:
                z = jax.lax.dot_general(
                    x, w,
                    dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=pref)
                if b is not None:
                    z = z + _bvec(b, z.ndim)
                out = jax.nn.gelu(z, approximate=approx)
            return out.astype(meta["out_dtype"])
        return call

    # LN family
    eps = meta["eps"]

    def call(*vals):
        it = iter(vals)
        if meta["res"]:
            x, res = next(it), next(it)
        else:
            x, res = next(it), None
        w = next(it) if meta["w"] else None
        b = next(it) if meta["b"] else None
        mw = next(it) if meta["matmul"] else None
        mb = next(it) if meta["mbias"] else None
        if backend == "pallas":
            d = x.shape[-1]
            rows = 1
            for s in x.shape[:-1]:
                rows *= s
            x2 = x.reshape(rows, d)
            r2 = res.reshape(rows, d) if res is not None else None
            if meta["matmul"]:
                y = fk.fused_ln_matmul(x2, mw, w, b, mb, r2,
                                       epsilon=eps, interpret=False)
                out = y.reshape(x.shape[:-1] + (mw.shape[1],))
            else:
                y = fk.fused_layer_norm(x2, w, b, r2, epsilon=eps,
                                        interpret=False)
                out = y.reshape(x.shape)
            return out.astype(meta["out_dtype"])
        # XLA mirror of the matched soup
        if res is not None:
            x = x + res
        xf = x.astype(meta.get("stats_dtype", jnp.float32))
        m = jnp.mean(xf, axis=-1, keepdims=True)
        va = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - m) * jax.lax.rsqrt(va + eps)
        # AMP casts back to the model dtype BEFORE the affine — mirror it
        y = y.astype(meta.get("affine_dtype", y.dtype))
        if w is not None:
            y = y * _bvec(w, y.ndim)
        if b is not None:
            y = y + _bvec(b, y.ndim)
        if meta["matmul"]:
            y = y.astype(meta["ln_dtype"])
            y = jax.lax.dot_general(
                y, mw, dimension_numbers=(((y.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=meta["pref"])
            if mb is not None:
                y = y + _bvec(mb, y.ndim)
        return y.astype(meta["out_dtype"])
    return call


def _eval_rewritten(jaxpr, consts, args, plan):
    """Evaluate ``jaxpr`` like ``core.eval_jaxpr`` but with each
    cluster's covered eqns skipped and its fused call bound at the
    cluster root."""
    env = {}

    def read(v):
        return v.val if _is_lit(v) else env[v]

    def write(v, val):
        env[v] = val

    for v, c in zip(jaxpr.constvars, consts):
        write(v, c)
    for v, a in zip(jaxpr.invars, args):
        write(v, a)

    by_idx = {}
    for cl in plan:
        fn = _cluster_fn(cl)
        for i in cl.covered:
            by_idx[i] = (cl, fn)

    for idx, eqn in enumerate(jaxpr.eqns):
        hit = by_idx.get(idx)
        if hit is not None:
            cl, fn = hit
            if idx != cl.root:
                continue
            write(cl.outvar, fn(*[read(v) for v in cl.invars]))
            continue
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        ans = eqn.primitive.bind(
            *subfuns, *[read(v) for v in eqn.invars], **bind_params)
        if eqn.primitive.multiple_results:
            for v, a in zip(eqn.outvars, ans):
                write(v, a)
        else:
            write(eqn.outvars[0], ans)
    return [read(v) for v in jaxpr.outvars]


def wrap(fn):
    """Apply the fusion pass to ``fn`` at trace time: re-trace it to a
    jaxpr, rewrite matched clusters to block-fused kernel calls, and
    evaluate the rewritten graph (in the caller's trace, so this
    composes with jit/grad/capture).  Falls back to ``fn`` untouched
    when the pass is disabled, nothing matches, or anything about the
    rewrite goes wrong — the pass must never break a model."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if not fusion_enabled():
            return fn(*args, **kwargs)
        try:
            flat, in_tree = jax.tree_util.tree_flatten((args, kwargs))

            def flat_fn(*leaves):
                a, kw = jax.tree_util.tree_unflatten(in_tree, leaves)
                return fn(*a, **kw)

            closed, out_shape = jax.make_jaxpr(
                flat_fn, return_shape=True)(*flat)
            plan = match_jaxpr(closed.jaxpr)
            if not plan:
                _stats["traces"] += 1
                return fn(*args, **kwargs)
        except Exception:
            return fn(*args, **kwargs)
        _stats["traces"] += 1
        for cl in plan:
            _note_rewrite(cl.pattern)
        out_flat = _eval_rewritten(closed.jaxpr, closed.consts, flat,
                                   plan)
        _, out_tree = jax.tree_util.tree_flatten(out_shape)
        return jax.tree_util.tree_unflatten(out_tree, out_flat)

    wrapped.__wrapped__ = fn
    return wrapped
