"""Op construction helpers.

The reference routes every op through a codegen'd C++ dispatch chain
(``paddle/phi/api/yaml/generator/api_gen.py`` → ``KernelFactory`` →
per-backend kernel). On TPU there is exactly one backend (XLA), so "an op" is
just a pure jax function plus tape recording — these helpers are the entire
replacement for the kernel registry + dispatch layer
(``paddle/phi/core/kernel_factory.h:61``).

AMP note: ops that the reference's auto-cast white-list promotes (matmul,
conv, ...) call ``maybe_autocast`` here, mirroring the AMP logic the
reference injects into generated forward functions
(``eager_gen.py:461 AMP_LOGIC_TEMPLATE``).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..autograd import record
from ..framework.dtype import to_jax_dtype


def ensure_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return Tensor(x, dtype=dtype)


def const(x):
    """Non-tensor operand: keep python scalars weakly typed, lift the rest."""
    if isinstance(x, (int, float, bool, complex)):
        return x
    return jnp.asarray(x)


_tensor_new = Tensor.__new__
_jax_types = (jax.Array, jax.core.Tracer)
try:  # concrete Array class — avoids backend-init-at-import a probe
    # array would cause; () makes the `type(x) is` fast check miss
    # harmlessly so the generic isinstance path still decides
    from jax._src.array import ArrayImpl as _array_impl
except Exception:  # pragma: no cover - jax internals moved
    _array_impl = ()


# -- per-op dispatch-key cache ----------------------------------------------
# jax.numpy elementwise ops (jnp.multiply, jnp.add, ...) are `ufunc`
# wrapper objects whose __call__ re-validates every operand on every
# call. Each wrapper carries a pre-jitted inner PjitFunction that takes
# the C++ fast dispatch path (~4µs cheaper per call on the bench box).
# Resolve that once per op and memoize — the analog of KernelFactory's
# op→kernel memo (``paddle/phi/core/kernel_factory.h:61``).
_DISPATCH_CACHE: dict = {}


def dispatch_target(fn):
    """Cheapest dispatchable form of ``fn``, resolved once per op.

    Keyed by id() — ufunc objects define a value-based __hash__ that
    costs more than the dispatch it would save; the cached entry keeps a
    strong ref to ``fn`` so the id stays valid."""
    cached = _DISPATCH_CACHE.get(id(fn))
    if cached is not None:
        return cached[1]
    target = fn
    props = getattr(fn, "_ufunc__static_props", None)
    if isinstance(props, dict):
        cand = props.get("call") or props.get("func")
        if callable(cand):
            target = cand
    _DISPATCH_CACHE[id(fn)] = (fn, target)
    return target


def _fast_tensor(raw, req):
    """Slot-writing Tensor constructor for op outputs — the eager hot
    path (SURVEY §3.1: the reference spends a codegen subsystem keeping
    per-op dispatch cheap; here it is skipping __init__'s conversion
    logic for already-jax outputs, ~2µs/op)."""
    # concrete-type check first: jax.Array is an ABC and its
    # instancecheck costs ~1µs even on cache hits
    if type(raw) is not _array_impl and not isinstance(raw, _jax_types):
        return Tensor(raw, stop_gradient=not req)
    t = _tensor_new(Tensor)
    t._data = raw
    t.stop_gradient = not req
    t._grad = None
    t._node = None
    t._out_idx = 0
    # t.name stays unset — lazily generated on first access
    t.persistable = False
    t.trainable = req
    t._grad_hooks = None
    t._spec = None
    return t


def _wrap_single(raw, req):
    t = _fast_tensor(raw, req)
    return [t], t


# record()'s single-output fast path dispatches on this identity — the
# wrap itself is only called when a custom recorder (static graph) or a
# future multi-wrap path needs the generic protocol
from ..autograd import _register_single_wrap  # noqa: E402
_register_single_wrap(_wrap_single, _fast_tensor)


def _wrap_tuple(raw, req):
    ts = tuple(_fast_tensor(r, req) for r in raw)
    return list(ts), ts


def unary(fn, x, name=""):
    x = ensure_tensor(x)
    return record(dispatch_target(fn), [x], _wrap_single, name=name)


def binary(fn, x, y, name=""):
    fn = dispatch_target(fn)
    tx, ty = isinstance(x, Tensor), isinstance(y, Tensor)
    if tx and ty:
        return record(fn, [x, y], _wrap_single, name=name)
    if tx:
        yv = const(y)
        return record(lambda a: fn(a, yv), [x], _wrap_single, name=name)
    if ty:
        xv = const(x)
        return record(lambda b: fn(xv, b), [y], _wrap_single, name=name)
    return record(fn, [ensure_tensor(x), ensure_tensor(y)], _wrap_single,
                  name=name)

def ternary(fn, x, y, z, name=""):
    return nary(fn, [x, y, z], name=name)


def nary(fn, args, name="", n_out=1):
    """fn over a mixed list of tensors/constants; constants closed over."""
    tensors, slots = [], []
    for a in args:
        if isinstance(a, Tensor):
            slots.append(len(tensors))
            tensors.append(a)
        else:
            slots.append(const(a))

    def packed(*datas):
        vals = [datas[s] if isinstance(s, int) else s for s in slots]
        return fn(*vals)

    wrap = _wrap_single if n_out == 1 else _wrap_tuple
    return record(packed, tensors, wrap, name=name)


def multi_out(fn, args, name="", grad_mask=None):
    """Op with tuple output (e.g. topk)."""
    return nary(fn, args, name=name, n_out=2_0000)  # any != 1 triggers tuple


def axis_tuple(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) % ndim if a < 0 else int(a) for a in axis)
    a = int(axis)
    return a + ndim if a < 0 else a


# -- AMP hook ---------------------------------------------------------------
def maybe_autocast(op_name, *tensors):
    """Cast inputs per active amp policy (O1 white/black list semantics,
    ref ``python/paddle/amp/auto_cast.py:271 amp_guard``)."""
    from .. import amp as _amp
    state = _amp._current_state()
    if state is None or not state.enable:
        return tensors
    return _amp._cast_for_op(state, op_name, tensors)
