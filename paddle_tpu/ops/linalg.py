"""Linear algebra ops (ref: ``python/paddle/tensor/linalg.py``).

`matmul` is THE op on TPU: it lowers to a single dot_general on the MXU.
The reference's call chain for this op is eight layers deep
(``linalg.py:139 matmul`` → ``_C_ops.matmul`` → generated ad_func → phi API →
kernel dispatch → cublas); here it is one jax call plus tape capture.

matmul/bmm participate in AMP O1 auto-cast (white list), mirroring
``eager_gen.py:461``'s generated AMP logic.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .op_utils import (ensure_tensor, unary as _unary, binary as _binary,
                       nary, maybe_autocast)

__all__ = [
    "matmul", "mm", "bmm", "dot", "mv", "dist", "norm", "cond",
    "cholesky", "cholesky_solve", "qr", "svd", "svdvals", "pca_lowrank", "lu",
    "lu_unpack", "inverse", "det", "slogdet", "solve", "triangular_solve",
    "lstsq", "matrix_power", "matrix_rank", "eig", "eigh", "eigvals",
    "eigvalsh", "pinv", "cross", "multi_dot", "corrcoef", "cov", "einsum",
    "householder_product", "matrix_exp", "vecdot", "vector_norm", "matrix_norm",
    "cdist",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = maybe_autocast("matmul", ensure_tensor(x), ensure_tensor(y))

    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return _binary(f, x, y, name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    x, y = maybe_autocast("bmm", ensure_tensor(x), ensure_tensor(y))
    return _binary(jnp.matmul, x, y, name="bmm")


def dot(x, y, name=None):
    def f(a, b):
        return jnp.sum(a * b, axis=-1)
    return _binary(f, x, y, name="dot")


def vecdot(x, y, axis=-1, name=None):
    return _binary(lambda a, b: jnp.sum(a * b, axis=axis), x, y, name="vecdot")


def mv(x, vec, name=None):
    return _binary(lambda a, b: jnp.matmul(a, b), x, vec, name="mv")


def dist(x, y, p=2, name=None):
    return _binary(lambda a, b: jnp.linalg.norm((a - b).ravel(), ord=p), x, y,
                   name="dist")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)

    def f(d):
        if axis is None and p is None:
            return jnp.linalg.norm(d.ravel(), ord=2, keepdims=False)
        if axis is None:
            return jnp.linalg.norm(d.ravel(), ord=p if p != "fro" else 2)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        ord_ = p
        if p == "fro":
            ord_ = "fro" if isinstance(ax, tuple) else 2
        elif p == "nuc":
            ord_ = "nuc"
        elif p is None:
            ord_ = 2 if not isinstance(ax, tuple) else "fro"
        if isinstance(ax, tuple) and not isinstance(ord_, str):
            # element-wise p-norm over multiple axes
            return jnp.sum(jnp.abs(d) ** ord_, axis=ax, keepdims=keepdim) ** (1.0 / ord_)
        return jnp.linalg.norm(d, ord=ord_, axis=ax, keepdims=keepdim)
    return _unary(f, x, name="norm")


def vector_norm(x, p=2, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return _unary(lambda d: jnp.linalg.vector_norm(d, ord=p, axis=ax,
                                                   keepdims=keepdim),
                  x, name="vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return _unary(lambda d: jnp.linalg.matrix_norm(d, ord=p, keepdims=keepdim),
                  x, name="matrix_norm")


def cond(x, p=None, name=None):
    return _unary(lambda d: jnp.linalg.cond(d, p=p), x, name="cond")


def cholesky(x, upper=False, name=None):
    def f(d):
        L = jnp.linalg.cholesky(d)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return _unary(f, x, name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        Lc = jnp.swapaxes(L, -1, -2).conj() if upper else L
        z = jax.scipy.linalg.solve_triangular(Lc, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(Lc, -1, -2).conj(), z, lower=False)
    return _binary(f, x, y, name="cholesky_solve")


def qr(x, mode="reduced", name=None):
    return nary(lambda d: tuple(jnp.linalg.qr(d, mode=mode)), [x],
                name="qr", n_out=2)


def svd(x, full_matrices=False, name=None):
    return nary(lambda d: tuple(jnp.linalg.svd(d, full_matrices=full_matrices)),
                [x], name="svd", n_out=3)


def svdvals(x, name=None):
    return _unary(lambda d: jnp.linalg.svdvals(d), x, name="svdvals")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = ensure_tensor(x)
    m, n = x.shape[-2], x.shape[-1]
    q = q if q is not None else min(6, m, n)

    def f(d):
        c = d - d.mean(axis=-2, keepdims=True) if center else d
        u, s, vt = jnp.linalg.svd(c, full_matrices=False)
        return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]
    return nary(f, [x], name="pca_lowrank", n_out=3)


def lu(x, pivot=True, get_infos=False, name=None):
    x = ensure_tensor(x)
    lu_, piv = jax.scipy.linalg.lu_factor(x._data)
    outs = (Tensor(lu_), Tensor((piv + 1).astype(jnp.int32)))
    if get_infos:
        return outs + (Tensor(jnp.zeros((), jnp.int32)),)
    return outs


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    lu_data = ensure_tensor(lu_data)
    n = lu_data.shape[-2]
    L = jnp.tril(lu_data._data, -1) + jnp.eye(n, lu_data.shape[-1])
    U = jnp.triu(lu_data._data)
    piv = np.asarray(ensure_tensor(lu_pivots)._data) - 1
    P = np.eye(n)
    perm = np.arange(n)
    for i, p in enumerate(piv.ravel()[:n]):
        perm[[i, p]] = perm[[p, i]]
    Pm = P[perm]
    return Tensor(jnp.asarray(Pm.T)), Tensor(L), Tensor(U)


def inverse(x, name=None):
    return _unary(jnp.linalg.inv, x, name="inverse")


def det(x, name=None):
    return _unary(jnp.linalg.det, x, name="det")


def slogdet(x, name=None):
    def f(d):
        sign, logdet = jnp.linalg.slogdet(d)
        return jnp.stack([sign, logdet])
    return _unary(f, x, name="slogdet")


def solve(x, y, name=None):
    def f(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)
    return _binary(f, x, y, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return _binary(f, x, y, name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    sol, res, rank_, sv = jnp.linalg.lstsq(x._data, y._data, rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(jnp.asarray(rank_)), Tensor(sv))


def matrix_power(x, n, name=None):
    return _unary(lambda d: jnp.linalg.matrix_power(d, n), x,
                  name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return _unary(lambda d: jnp.linalg.matrix_rank(d, rtol=tol).astype(jnp.int32),
                  x, name="matrix_rank")


def matrix_exp(x, name=None):
    return _unary(jax.scipy.linalg.expm, x, name="matrix_exp")


def eig(x, name=None):
    x = ensure_tensor(x)
    # general eig is CPU-only in every backend; route via host (same as the
    # reference, which runs LAPACK on CPU for eig)
    w, v = np.linalg.eig(np.asarray(x._data))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(x._data))))


def eigh(x, UPLO="L", name=None):
    return nary(lambda d: tuple(jnp.linalg.eigh(d, symmetrize_input=True)),
                [x], name="eigh", n_out=2)


def eigvalsh(x, UPLO="L", name=None):
    return _unary(lambda d: jnp.linalg.eigvalsh(d), x, name="eigvalsh")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _unary(lambda d: jnp.linalg.pinv(d, rtol=rcond,
                                            hermitian=hermitian), x,
                  name="pinv")


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return _binary(f, x, y, name="cross")


def multi_dot(tensors, name=None):
    return nary(lambda *ds: jnp.linalg.multi_dot(ds),
                [ensure_tensor(t) for t in tensors], name="multi_dot")


def corrcoef(x, rowvar=True, name=None):
    return _unary(lambda d: jnp.corrcoef(d, rowvar=rowvar), x, name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = ensure_tensor(fweights)._data if fweights is not None else None
    aw = ensure_tensor(aweights)._data if aweights is not None else None
    return _unary(lambda d: jnp.cov(d, rowvar=rowvar,
                                    ddof=1 if ddof else 0,
                                    fweights=fw, aweights=aw), x, name="cov")


def einsum(equation, *operands, name=None):
    ops_ = [ensure_tensor(o) for o in operands]
    ops_ = list(maybe_autocast("einsum", *ops_))
    return nary(lambda *ds: jnp.einsum(equation, *ds), ops_, name="einsum")


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)

        def body(i, Q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i])
            v = v.at[i].set(1.0)
            H = eye - t[..., i] * jnp.outer(v, v)
            return Q @ H
        Q = eye
        for i in range(n):
            Q = body(i, Q)
        return Q[..., :, :n]
    return _binary(f, x, tau, name="householder_product")


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Batched pairwise p-norm distance (ref: ``tensor/linalg.py:3484``).

    TPU design: for p=2 with the mm compute modes, use the expanded
    ``|x|^2 + |y|^2 - 2 x.y^T`` form — one MXU matmul instead of an
    O(P*R*M) broadcast — unless the caller forces the naive path.
    """
    if p < 0:
        raise ValueError("cdist only supports non-negative p values")

    def f(a, b):
        if p == 2.0 and compute_mode != "donot_use_mm_for_euclid_dist":
            x2 = jnp.sum(a * a, axis=-1)[..., :, None]
            y2 = jnp.sum(b * b, axis=-1)[..., None, :]
            xy = jnp.matmul(a, jnp.swapaxes(b, -1, -2))
            sq = jnp.maximum(x2 + y2 - 2.0 * xy, 0.0)
            # double-where: zero subgradient at coincident points instead
            # of sqrt'(0)=inf NaN-poisoning the backward
            safe = jnp.where(sq > 0.0, sq, 1.0)
            return jnp.where(sq > 0.0, jnp.sqrt(safe), 0.0)
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 0.0:
            return jnp.sum((d != 0).astype(a.dtype), axis=-1)
        import math
        # p is the host-side norm order (a python scalar), not a
        # device value — no transfer happens here
        # tpu-lint: disable=TPU017
        if math.isinf(float(p)):
            return jnp.max(jnp.abs(d), axis=-1)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return _binary(f, x, y, name="cdist")
