"""Fused Pallas kernels for the non-attention hot fusion clusters.

The reference ships these as hand-written CUDA under
``paddle/phi/kernels/fusion/`` (``fused_layernorm_kernel.cu``,
``cross_entropy_kernel.cu``); XLA fuses the elementwise pieces but still
materialises the (B, V) probability matrix for cross-entropy and runs
layernorm's stats as separate reductions.  Two kernels close that gap:

 - :func:`fused_layer_norm` — one-pass (sum / sum-of-squares) mean+var
   in f32 over MXU-aligned row tiles, optional fused residual add,
   forward + backward as one ``jax.custom_vjp`` (the backward emits dx
   and accumulates dweight/dbias across row tiles in a single kernel).
 - :func:`fused_softmax_xent` — softmax-cross-entropy with an online
   logsumexp over vocab tiles so the (rows, V) probability matrix never
   exists in HBM; ``ignore_index`` and label smoothing fold into the
   tile loop, and the backward emits ``softmax(x) - onehot`` in one
   pass from the saved logsumexp.

PR 12 adds the *block-fused* kernels targeted by the jaxpr fusion pass
(:mod:`.fusion_pass`) — whole transformer sub-blocks as one launch:

 - :func:`fused_ln_matmul` — (residual +) LayerNorm + matmul epilogue
   (+ bias): the LN output never round-trips to HBM before the MXU.
 - :func:`fused_matmul_bias_gelu` — the MLP up-projection
   ``gelu(x @ W + b)`` with the activation applied on the accumulator.
 - :func:`fused_attention_block` — qkv-matmul + scale + softmax +
   pv-matmul, delegating to the flash kernel in :mod:`.pallas_ops`.

All run in Pallas interpret mode off-TPU (tier-1 correctness), follow
the MXU contract from :mod:`.pallas_ops` (native-dtype operands, f32
accumulation), and read their launch configs from the search-based
tuner in :mod:`.autotune`.  The PR 8 kernels search static candidate
tables; the block kernels' tuners (``tune_ln_matmul`` /
``tune_matmul_bias_gelu``) feed :func:`autotune.generate_candidates`
instead — the cost model *emits* the tile space from the cluster shape
and prunes it before anything is timed.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ops import _CompilerParams, _LANES, _NEG_INF, _ceil_to, \
    _interpret_default

__all__ = [
    "fused_layer_norm", "fused_softmax_xent",
    "fused_ln_matmul", "fused_matmul_bias_gelu", "fused_attention_block",
    "layer_norm_reference", "softmax_xent_reference",
    "ln_matmul_reference", "matmul_bias_gelu_reference",
    "attention_block_reference",
    "tune_layer_norm", "tune_softmax_xent",
    "tune_ln_matmul", "tune_matmul_bias_gelu",
    "LN_CANDIDATES", "XENT_CANDIDATES", "record_dispatch",
]


# ---------------------------------------------------------------------------
# dispatch observability
# ---------------------------------------------------------------------------
def record_dispatch(kernel: str, path: str):
    """Count one dispatch decision: ``path`` is ``pallas`` (fused kernel
    taken) or ``fallback`` (XLA path). Fed by the nn.functional dispatch
    layer; never raises. Looked up per call (not cached) so a registry
    reset doesn't strand increments on a stale counter — dispatch
    decisions are trace-time events, not hot-loop work. Inert while
    telemetry is off (the registry must stay empty then)."""
    try:
        from ..observability.metrics import get_registry
        from ..observability.telemetry import get_telemetry
        if not get_telemetry().enabled:
            return
        get_registry().counter(
            "pt_pallas_calls_total",
            "Kernel dispatch decisions by path (pallas|fallback)",
            labelnames=("kernel", "path")).inc(kernel=kernel, path=path)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------
def _ln_refs(refs, has_res, has_w, has_b, n_out):
    """Split a layernorm kernel's ref list into (inputs..., outputs)."""
    i = 1
    x_ref = refs[0]
    res_ref = w_ref = b_ref = None
    if has_res:
        res_ref, i = refs[i], i + 1
    if has_w:
        w_ref, i = refs[i], i + 1
    if has_b:
        b_ref, i = refs[i], i + 1
    return x_ref, res_ref, w_ref, b_ref, refs[i:i + n_out], refs[i + n_out:]


def _ln_fwd_kernel(*refs, d, eps, block_rows, d_pad, has_res, has_w, has_b):
    x_ref, res_ref, w_ref, b_ref, (y_ref, mean_ref, rstd_ref), _ = _ln_refs(
        refs, has_res, has_w, has_b, 3)
    xv = x_ref[:].astype(jnp.float32)
    if has_res:
        xv = xv + res_ref[:].astype(jnp.float32)
    if d_pad != d:
        colmask = jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, d_pad), 1) < d
        xm = jnp.where(colmask, xv, 0.0)
    else:
        colmask, xm = None, xv
    # one-pass mean/var in f32: E[x] and E[x^2] from a single read of the
    # tile (the Welford-style single-visit stats the CUDA kernel uses)
    s1 = jnp.sum(xm, axis=-1, keepdims=True)
    s2 = jnp.sum(xm * xm, axis=-1, keepdims=True)
    mean = s1 / d
    var = jnp.maximum(s2 / d - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    y = (xv - mean) * rstd
    if colmask is not None:
        y = jnp.where(colmask, y, 0.0)
    if has_w:
        y = y * w_ref[:].astype(jnp.float32)
    if has_b:
        y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(*refs, d, block_rows, d_pad, has_res, has_w, has_b):
    x_ref, res_ref, w_ref, b_ref, (g_ref, mean_ref, rstd_ref), outs = \
        _ln_refs(refs, has_res, has_w, has_b, 3)
    dx_ref = outs[0]
    dw_ref = outs[1] if has_w else None
    db_ref = outs[1 + int(has_w)] if has_b else None

    xv = x_ref[:].astype(jnp.float32)
    if has_res:
        xv = xv + res_ref[:].astype(jnp.float32)
    gv = g_ref[:].astype(jnp.float32)
    if d_pad != d:
        colmask = jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, d_pad), 1) < d
        gv = jnp.where(colmask, gv, 0.0)
    else:
        colmask = None
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (xv - mean) * rstd
    if colmask is not None:
        xhat = jnp.where(colmask, xhat, 0.0)
    dy = gv * w_ref[:].astype(jnp.float32) if has_w else gv
    c1 = jnp.sum(dy, axis=-1, keepdims=True) / d
    c2 = jnp.sum(dy * xhat, axis=-1, keepdims=True) / d
    dx = (dy - c1 - xhat * c2) * rstd
    if colmask is not None:
        dx = jnp.where(colmask, dx, 0.0)
    dx_ref[:] = dx.astype(dx_ref.dtype)

    if has_w or has_b:
        # param grads accumulate across row tiles: the grid dim is
        # "arbitrary" so revisiting the single (1, d_pad) output block
        # is sequential (same trick as the flash dkv accumulator)
        @pl.when(pl.program_id(0) == 0)
        def _init():
            if has_w:
                dw_ref[:] = jnp.zeros(dw_ref.shape, jnp.float32)
            if has_b:
                db_ref[:] = jnp.zeros(db_ref.shape, jnp.float32)

        if has_w:
            dw_ref[:] = dw_ref[:] + jnp.sum(gv * xhat, axis=0, keepdims=True)
        if has_b:
            db_ref[:] = db_ref[:] + jnp.sum(gv, axis=0, keepdims=True)


def _ln_pallas_fwd(x, res, w, b, *, d, eps, block_rows, parallel, interpret):
    rows_p, d_pad = x.shape
    ni = rows_p // block_rows
    has_res, has_w, has_b = res is not None, w is not None, b is not None
    row_spec = pl.BlockSpec((block_rows, d_pad), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d_pad), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((block_rows, 1), lambda i: (i, 0))
    in_specs = [row_spec]
    args = [x]
    if has_res:
        in_specs.append(row_spec)
        args.append(res)
    if has_w:
        in_specs.append(vec_spec)
        args.append(w)
    if has_b:
        in_specs.append(vec_spec)
        args.append(b)
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, d=d, eps=eps,
                          block_rows=block_rows, d_pad=d_pad,
                          has_res=has_res, has_w=has_w, has_b=has_b),
        grid=(ni,),
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, d_pad), x.dtype),
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel" if parallel else "arbitrary",)),
        interpret=interpret,
    )(*args)


def _ln_pallas_bwd(x, res, w, b, g, mean, rstd, *, d, block_rows,
                   interpret):
    rows_p, d_pad = x.shape
    ni = rows_p // block_rows
    has_res, has_w, has_b = res is not None, w is not None, b is not None
    row_spec = pl.BlockSpec((block_rows, d_pad), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d_pad), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((block_rows, 1), lambda i: (i, 0))
    in_specs = [row_spec]
    args = [x]
    if has_res:
        in_specs.append(row_spec)
        args.append(res)
    if has_w:
        in_specs.append(vec_spec)
        args.append(w)
    if has_b:
        in_specs.append(vec_spec)
        args.append(b)
    in_specs += [row_spec, stat_spec, stat_spec]
    args += [g, mean, rstd]
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows_p, d_pad), x.dtype)]
    if has_w:
        out_specs.append(vec_spec)
        out_shape.append(jax.ShapeDtypeStruct((1, d_pad), jnp.float32))
    if has_b:
        out_specs.append(vec_spec)
        out_shape.append(jax.ShapeDtypeStruct((1, d_pad), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, d=d, block_rows=block_rows,
                          d_pad=d_pad, has_res=has_res, has_w=has_w,
                          has_b=has_b),
        grid=(ni,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*args)
    dx = outs[0]
    dw = outs[1] if has_w else None
    db = outs[1 + int(has_w)] if has_b else None
    return dx, dw, db


_LN_STATICS = (4, 5, 6, 7, 8)


@functools.partial(jax.custom_vjp, nondiff_argnums=_LN_STATICS)
def _ln(x, w, b, res, d, eps, block_rows, parallel, interpret):
    y, _, _ = _ln_pallas_fwd(x, res, w, b, d=d, eps=eps,
                             block_rows=block_rows, parallel=parallel,
                             interpret=interpret)
    return y


def _ln_fwd(x, w, b, res, d, eps, block_rows, parallel, interpret):
    y, mean, rstd = _ln_pallas_fwd(x, res, w, b, d=d, eps=eps,
                                   block_rows=block_rows, parallel=parallel,
                                   interpret=interpret)
    return y, (x, w, b, res, mean, rstd)


def _ln_bwd(d, eps, block_rows, parallel, interpret, residuals, g):
    x, w, b, res, mean, rstd = residuals
    dx, dw, db = _ln_pallas_bwd(x, res, w, b, g, mean, rstd, d=d,
                                block_rows=block_rows, interpret=interpret)
    return (dx,
            None if w is None else dw.astype(w.dtype),
            None if b is None else db.astype(b.dtype),
            None if res is None else dx.astype(res.dtype))


_ln.defvjp(_ln_fwd, _ln_bwd)


def _ln_tune_key(rows, d, dtype, interpret):
    return (rows, d, str(dtype), bool(interpret))


def fused_layer_norm(x, weight=None, bias=None, residual=None, *,
                     epsilon=1e-5, block_rows=None, parallel=True,
                     interpret=None):
    """Fused layernorm over a 2-D (rows, d) view; normalizes each row.

    ``residual`` (same shape as ``x``) is added before normalization —
    the transformer block's residual+LN cluster in one kernel launch.
    Returns the normalized array in ``x.dtype``; stats are f32.

    ``block_rows``/``parallel`` default to the autotuned choice when
    :func:`tune_layer_norm` has cached one (see :mod:`.autotune`),
    else 256 rows with a parallel grid.
    """
    if x.ndim != 2:
        raise ValueError(f"fused_layer_norm expects 2-D input, got {x.shape}")
    if interpret is None:
        interpret = _interpret_default()
    rows, d = x.shape
    if block_rows is None:
        from . import autotune as _at
        hit = _at.cache_get("fused_layer_norm", _ln_tune_key(
            rows, d, x.dtype, interpret)) if _at.enabled() else None
        if hit is not None:
            block_rows, parallel = int(hit[0]), bool(hit[1])
        else:
            block_rows = 256
    block_rows = min(int(block_rows), _ceil_to(rows, 8))
    d_pad = _ceil_to(d, _LANES)
    rows_p = _ceil_to(rows, block_rows)

    xp = jnp.pad(x, ((0, rows_p - rows), (0, d_pad - d)))
    wp = bp = rp = None
    if weight is not None:
        wp = jnp.pad(jnp.reshape(weight, (1, d)), ((0, 0), (0, d_pad - d)))
    if bias is not None:
        bp = jnp.pad(jnp.reshape(bias, (1, d)), ((0, 0), (0, d_pad - d)))
    if residual is not None:
        rp = jnp.pad(residual, ((0, rows_p - rows), (0, d_pad - d)))
    y = _ln(xp, wp, bp, rp, d, float(epsilon), block_rows, bool(parallel),
            interpret)
    return y[:rows, :d]


def layer_norm_reference(x, weight=None, bias=None, residual=None,
                         epsilon=1e-5):
    """Pure-jnp reference for the unit tests ((rows, d) layout)."""
    xv = x.astype(jnp.float32)
    if residual is not None:
        xv = xv + residual.astype(jnp.float32)
    m = jnp.mean(xv, axis=-1, keepdims=True)
    v = jnp.var(xv, axis=-1, keepdims=True)
    out = (xv - m) * jax.lax.rsqrt(v + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# fused softmax cross-entropy
# ---------------------------------------------------------------------------
def _xent_fwd_kernel(lab_ref, x_ref, loss_ref, lse_ref, m_scr, l_scr,
                     t_scr, s_scr, *, V, block_rows, block_v,
                     ignore_index, smoothing):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        t_scr[:] = jnp.zeros(t_scr.shape, jnp.float32)
        s_scr[:] = jnp.zeros(s_scr.shape, jnp.float32)

    xv = x_ref[:].astype(jnp.float32)
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_rows, block_v), 1)
    colmask = col < V
    xm = jnp.where(colmask, xv, _NEG_INF)

    # online logsumexp: running max m, rescaled running sum l — the
    # (rows, V) probability matrix never leaves this tile
    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_cur = jnp.max(xm, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.where(colmask, jnp.exp(xm - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)

    lab = lab_ref[:]                    # (block_rows, 1) int32
    lab_c = jnp.clip(lab, 0, V - 1)
    # target logit and (for label smoothing) the running logit sum fold
    # into the same tile visit
    t_new = t_scr[:, :1] + jnp.sum(
        jnp.where(col == lab_c, xv, 0.0), axis=-1, keepdims=True)
    s_new = s_scr[:, :1] + jnp.sum(
        jnp.where(colmask, xv, 0.0), axis=-1, keepdims=True)

    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
    t_scr[:] = jnp.broadcast_to(t_new, t_scr.shape)
    s_scr[:] = jnp.broadcast_to(s_new, s_scr.shape)

    @pl.when(j == nv - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        lse = m_scr[:, :1] + jnp.log(l_safe)
        t = t_scr[:, :1]
        loss = lse - t
        if smoothing > 0.0:
            # (1-ls)*(lse - x_y) + ls*(lse - mean(x)) folded:
            loss = lse - (1.0 - smoothing) * t \
                - smoothing * (s_scr[:, :1] / V)
        valid = lab != ignore_index
        loss_ref[:] = jnp.where(valid, loss, 0.0)
        lse_ref[:] = lse


def _xent_bwd_kernel(lab_ref, x_ref, lse_ref, g_ref, dx_ref, *, V,
                     block_rows, block_v, ignore_index, smoothing):
    j = pl.program_id(1)
    xv = x_ref[:].astype(jnp.float32)
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_rows, block_v), 1)
    colmask = col < V
    # softmax(x) - onehot in ONE pass from the saved logsumexp
    p = jnp.where(colmask, jnp.exp(xv - lse_ref[:]), 0.0)
    lab = lab_ref[:]
    lab_c = jnp.clip(lab, 0, V - 1)
    onehot = jnp.logical_and(col == lab_c, colmask)
    grad = p - (1.0 - smoothing) * onehot.astype(jnp.float32)
    if smoothing > 0.0:
        grad = grad - jnp.where(colmask, smoothing / V, 0.0)
    valid = lab != ignore_index
    dx = g_ref[:] * jnp.where(valid, grad, 0.0)
    dx_ref[:] = jnp.where(colmask, dx, 0.0).astype(dx_ref.dtype)


def _xent_pallas_fwd(x, lab, *, V, block_rows, block_v, ignore_index,
                     smoothing, interpret):
    rows_p, v_pad = x.shape
    ni, nv = rows_p // block_rows, v_pad // block_v
    lab_spec = pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0))
    stat_spec = pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0))
    return pl.pallas_call(
        functools.partial(_xent_fwd_kernel, V=V, block_rows=block_rows,
                          block_v=block_v, ignore_index=ignore_index,
                          smoothing=smoothing),
        grid=(ni, nv),
        in_specs=[
            lab_spec,
            pl.BlockSpec((block_rows, block_v), lambda i, j: (i, j)),
        ],
        out_specs=[stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows, _LANES), jnp.float32),
            pltpu.VMEM((block_rows, _LANES), jnp.float32),
            pltpu.VMEM((block_rows, _LANES), jnp.float32),
            pltpu.VMEM((block_rows, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lab, x)


def _xent_pallas_bwd(x, lab, lse, g, *, V, block_rows, block_v,
                     ignore_index, smoothing, interpret):
    rows_p, v_pad = x.shape
    ni, nv = rows_p // block_rows, v_pad // block_v
    stat_spec = pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0))
    return pl.pallas_call(
        functools.partial(_xent_bwd_kernel, V=V, block_rows=block_rows,
                          block_v=block_v, ignore_index=ignore_index,
                          smoothing=smoothing),
        grid=(ni, nv),
        in_specs=[
            stat_spec,
            pl.BlockSpec((block_rows, block_v), lambda i, j: (i, j)),
            stat_spec,
            stat_spec,
        ],
        out_specs=pl.BlockSpec((block_rows, block_v), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((rows_p, v_pad), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(lab, x, lse, g)


_XENT_STATICS = (2, 3, 4, 5, 6, 7)


@functools.partial(jax.custom_vjp, nondiff_argnums=_XENT_STATICS)
def _xent(x, lab_f32, V, block_rows, block_v, ignore_index, smoothing,
          interpret):
    lab = jax.lax.bitcast_convert_type(lab_f32, jnp.int32)
    loss, _ = _xent_pallas_fwd(x, lab, V=V, block_rows=block_rows,
                               block_v=block_v, ignore_index=ignore_index,
                               smoothing=smoothing, interpret=interpret)
    return loss


def _xent_fwd(x, lab_f32, V, block_rows, block_v, ignore_index, smoothing,
              interpret):
    lab = jax.lax.bitcast_convert_type(lab_f32, jnp.int32)
    loss, lse = _xent_pallas_fwd(x, lab, V=V, block_rows=block_rows,
                                 block_v=block_v, ignore_index=ignore_index,
                                 smoothing=smoothing, interpret=interpret)
    return loss, (x, lab_f32, lse)


def _xent_bwd(V, block_rows, block_v, ignore_index, smoothing, interpret,
              residuals, g):
    x, lab_f32, lse = residuals
    lab = jax.lax.bitcast_convert_type(lab_f32, jnp.int32)
    dx = _xent_pallas_bwd(x, lab, lse, g.astype(jnp.float32), V=V,
                          block_rows=block_rows, block_v=block_v,
                          ignore_index=ignore_index, smoothing=smoothing,
                          interpret=interpret)
    return dx, jnp.zeros_like(lab_f32)


_xent.defvjp(_xent_fwd, _xent_bwd)


def _xent_tune_key(rows, V, dtype, smoothing, interpret):
    return (rows, V, str(dtype), smoothing > 0.0, bool(interpret))


def fused_softmax_xent(logits, labels, *, ignore_index=-100,
                       label_smoothing=0.0, block_rows=None, block_v=None,
                       interpret=None):
    """Per-row softmax-cross-entropy loss over 2-D (rows, V) logits.

    ``labels`` is int (rows,) — rows whose label equals ``ignore_index``
    get loss 0 (callers own the mean-over-valid normalization).  Returns
    f32 (rows,).  Launch config comes from the tuner cache when
    :func:`tune_softmax_xent` has populated it, else (256, 512).
    """
    if logits.ndim != 2:
        raise ValueError(
            f"fused_softmax_xent expects 2-D logits, got {logits.shape}")
    if interpret is None:
        interpret = _interpret_default()
    rows, V = logits.shape
    if block_rows is None and block_v is None:
        from . import autotune as _at
        hit = _at.cache_get("fused_softmax_xent", _xent_tune_key(
            rows, V, logits.dtype, label_smoothing,
            interpret)) if _at.enabled() else None
        if hit is not None:
            block_rows, block_v = int(hit[0]), int(hit[1])
    block_rows = 256 if block_rows is None else int(block_rows)
    block_v = 512 if block_v is None else int(block_v)
    block_rows = min(block_rows, _ceil_to(rows, 8))
    block_v = min(block_v, _ceil_to(V, _LANES))
    rows_p = _ceil_to(rows, block_rows)
    v_pad = _ceil_to(V, block_v)

    xp = jnp.pad(logits, ((0, rows_p - rows), (0, v_pad - V)))
    lab = jnp.asarray(labels, jnp.int32).reshape(rows)
    lab = jnp.pad(lab, (0, rows_p - rows),
                  constant_values=int(ignore_index))
    lab_f32 = jax.lax.bitcast_convert_type(lab.reshape(rows_p, 1),
                                           jnp.float32)
    loss = _xent(xp, lab_f32, V, block_rows, block_v, int(ignore_index),
                 float(label_smoothing), interpret)
    return loss[:rows, 0]


def softmax_xent_reference(logits, labels, *, ignore_index=-100,
                           label_smoothing=0.0):
    """Pure-jnp reference for the unit tests ((rows, V), int labels)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    V = logits.shape[-1]
    lab = jnp.asarray(labels, jnp.int32).reshape(-1)
    onehot_ll = jnp.take_along_axis(
        logp, jnp.clip(lab, 0, V - 1)[:, None], axis=-1)[:, 0]
    loss = -onehot_ll
    if label_smoothing > 0:
        loss = (1 - label_smoothing) * loss \
            + label_smoothing * (-jnp.mean(logp, axis=-1))
    return jnp.where(lab != ignore_index, loss, 0.0)


# ---------------------------------------------------------------------------
# autotune candidate spaces + cost seeds
# ---------------------------------------------------------------------------
# (block_rows, parallel-grid?) — semantics is part of the search space:
# "parallel" lets Mosaic pipeline row tiles, "arbitrary" forces the
# sequential revisit order (wins when tiles are few and large)
LN_CANDIDATES = ((128, 1), (256, 1), (512, 1), (1024, 1), (256, 0),
                 (1024, 0))
# (block_rows, block_v)
XENT_CANDIDATES = ((128, 512), (256, 512), (256, 1024), (512, 512),
                   (512, 1024), (1024, 512))

_F32 = 4


def _ln_cost_fn(rows, d, itemsize):
    """Per-candidate cost estimate for the layernorm search, seeded by
    the cost model's analytic FLOPs/bytes of the jnp reference."""
    from . import autotune as _at
    d_pad = _ceil_to(d, _LANES)
    sample = jnp.zeros((min(rows, 1024), d), jnp.float32)
    seed = _at.analytic_seed(
        lambda a: layer_norm_reference(a, jnp.ones((d,), jnp.float32),
                                       jnp.zeros((d,), jnp.float32)),
        sample)
    scale = rows / max(sample.shape[0], 1)
    flops = seed["flops"] * scale if seed else rows * d * 8.0
    bytes_ = seed["bytes"] * scale if seed else rows * d * itemsize * 2.0

    def cost(cfg):
        br = min(int(cfg[0]), _ceil_to(rows, 8))
        # working set: input + residual/output tiles in native dtype,
        # an f32 compute copy, the weight/bias vectors and row stats
        vmem = (2 * br * d_pad * itemsize + br * d_pad * _F32
                + 2 * d_pad * _F32 + 2 * br * _F32)
        return {"flops": flops, "bytes": bytes_, "vmem_bytes": vmem,
                "mxu_underfill": br < 8}
    return cost


def _xent_cost_fn(rows, V, itemsize):
    from . import autotune as _at
    sample_rows = min(rows, 512)
    sample = jnp.zeros((sample_rows, V), jnp.float32)
    lab = jnp.zeros((sample_rows,), jnp.int32)
    seed = _at.analytic_seed(
        lambda a, y: softmax_xent_reference(a, y), sample, lab)
    scale = rows / max(sample_rows, 1)
    flops = seed["flops"] * scale if seed else rows * V * 6.0
    bytes_ = seed["bytes"] * scale if seed else rows * V * itemsize * 2.0

    def cost(cfg):
        br = min(int(cfg[0]), _ceil_to(rows, 8))
        bv = min(int(cfg[1]), _ceil_to(V, _LANES))
        vmem = (br * bv * itemsize + br * bv * _F32
                + 4 * br * _LANES * _F32 + 3 * br * _F32)
        return {"flops": flops, "bytes": bytes_, "vmem_bytes": vmem,
                "mxu_underfill": br < 8 or bv < _LANES}
    return cost


def tune_layer_norm(x, weight=None, bias=None, *, epsilon=1e-5,
                    interpret=None, candidates=LN_CANDIDATES):
    """Eagerly search layernorm launch configs for this (rows, d, dtype)
    and cache the winner (see :func:`autotune.search`). ``x`` is the 2-D
    (rows, d) array the hot path will see. Returns (best, timings)."""
    from . import autotune as _at

    if interpret is None:
        interpret = _interpret_default()
    rows, d = x.shape
    seen, todo = set(), []
    for br, par in candidates:
        clamped = (min(int(br), _ceil_to(rows, 8)), int(par))
        if clamped not in seen:
            seen.add(clamped)
            todo.append(clamped)

    state = {"x": x}

    def run(cfg):
        # thread the output back in + host readback fence (see tune_mha)
        out = fused_layer_norm(state["x"], weight, bias, epsilon=epsilon,
                               block_rows=cfg[0], parallel=bool(cfg[1]),
                               interpret=interpret)
        state["x"] = (out.astype(jnp.float32) * 1e-3).astype(x.dtype)
        float(jnp.sum(state["x"].astype(jnp.float32)))

    best, timings = _at.search(
        "fused_layer_norm", _ln_tune_key(rows, d, x.dtype, interpret),
        run, todo, cost=_ln_cost_fn(rows, d, x.dtype.itemsize))
    _at.set_enabled(True)
    return best, timings


def tune_softmax_xent(logits, labels, *, ignore_index=-100,
                      label_smoothing=0.0, interpret=None,
                      candidates=XENT_CANDIDATES):
    """Eagerly search softmax-xent launch configs for this (rows, V,
    dtype) and cache the winner. Returns (best, timings)."""
    from . import autotune as _at

    if interpret is None:
        interpret = _interpret_default()
    rows, V = logits.shape
    seen, todo = set(), []
    for br, bv in candidates:
        clamped = (min(int(br), _ceil_to(rows, 8)),
                   min(int(bv), _ceil_to(V, _LANES)))
        if clamped not in seen:
            seen.add(clamped)
            todo.append(clamped)

    state = {"x": logits}

    def run(cfg):
        loss = fused_softmax_xent(
            state["x"], labels, ignore_index=ignore_index,
            label_smoothing=label_smoothing, block_rows=cfg[0],
            block_v=cfg[1], interpret=interpret)
        state["x"] = state["x"] + (jnp.mean(loss) * 1e-6).astype(
            logits.dtype)
        float(jnp.sum(loss))

    best, timings = _at.search(
        "fused_softmax_xent",
        _xent_tune_key(rows, V, logits.dtype, label_smoothing, interpret),
        run, todo, cost=_xent_cost_fn(rows, V, logits.dtype.itemsize))
    _at.set_enabled(True)
    return best, timings


# ---------------------------------------------------------------------------
# block-fused: (residual +) layernorm + matmul epilogue
# ---------------------------------------------------------------------------
def _lnmm_fwd_kernel(*refs, d, eps, block_rows, d_pad, has_res, has_lw,
                     has_lb, has_mb):
    it = iter(refs)
    x_ref = next(it)
    res_ref = next(it) if has_res else None
    lw_ref = next(it) if has_lw else None
    lb_ref = next(it) if has_lb else None
    w_ref = next(it)
    mb_ref = next(it) if has_mb else None
    y_ref = next(it)

    xv = x_ref[:].astype(jnp.float32)
    if has_res:
        xv = xv + res_ref[:].astype(jnp.float32)
    if d_pad != d:
        colmask = jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, d_pad), 1) < d
        xm = jnp.where(colmask, xv, 0.0)
    else:
        colmask, xm = None, xv
    s1 = jnp.sum(xm, axis=-1, keepdims=True)
    s2 = jnp.sum(xm * xm, axis=-1, keepdims=True)
    mean = s1 / d
    var = jnp.maximum(s2 / d - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    h = (xv - mean) * rstd
    if has_lw:
        h = h * lw_ref[:].astype(jnp.float32)
    if has_lb:
        h = h + lb_ref[:].astype(jnp.float32)
    if colmask is not None:
        # padded lanes must be exact zeros before they reach the MXU
        h = jnp.where(colmask, h, 0.0)
    acc = jax.lax.dot_general(
        h.astype(x_ref.dtype), w_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if has_mb:
        acc = acc + mb_ref[:].astype(jnp.float32)
    y_ref[:] = acc.astype(y_ref.dtype)


def _lnmm_pallas_fwd(x, res, lw, lb, w, mb, *, d, eps, block_rows,
                     block_n, parallel, interpret):
    rows_p, d_pad = x.shape
    n_pad = w.shape[1]
    ni, nj = rows_p // block_rows, n_pad // block_n
    has_res, has_lw = res is not None, lw is not None
    has_lb, has_mb = lb is not None, mb is not None
    row_spec = pl.BlockSpec((block_rows, d_pad), lambda i, j: (i, 0))
    vec_spec = pl.BlockSpec((1, d_pad), lambda i, j: (0, 0))
    in_specs, args = [row_spec], [x]
    if has_res:
        in_specs.append(row_spec)
        args.append(res)
    if has_lw:
        in_specs.append(vec_spec)
        args.append(lw)
    if has_lb:
        in_specs.append(vec_spec)
        args.append(lb)
    in_specs.append(pl.BlockSpec((d_pad, block_n), lambda i, j: (0, j)))
    args.append(w)
    if has_mb:
        in_specs.append(pl.BlockSpec((1, block_n), lambda i, j: (0, j)))
        args.append(mb)
    return pl.pallas_call(
        functools.partial(_lnmm_fwd_kernel, d=d, eps=eps,
                          block_rows=block_rows, d_pad=d_pad,
                          has_res=has_res, has_lw=has_lw, has_lb=has_lb,
                          has_mb=has_mb),
        grid=(ni, nj),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_rows, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((rows_p, n_pad), x.dtype),
        compiler_params=_CompilerParams(dimension_semantics=(
            "parallel" if parallel else "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*args)


_LNMM_STATICS = (6, 7, 8, 9, 10, 11)


@functools.partial(jax.custom_vjp, nondiff_argnums=_LNMM_STATICS)
def _lnmm(x, w, lw, lb, mb, res, d, eps, block_rows, block_n, parallel,
          interpret):
    return _lnmm_pallas_fwd(x, res, lw, lb, w, mb, d=d, eps=eps,
                            block_rows=block_rows, block_n=block_n,
                            parallel=parallel, interpret=interpret)


def _lnmm_fwd(x, w, lw, lb, mb, res, d, eps, block_rows, block_n,
              parallel, interpret):
    y = _lnmm_pallas_fwd(x, res, lw, lb, w, mb, d=d, eps=eps,
                         block_rows=block_rows, block_n=block_n,
                         parallel=parallel, interpret=interpret)
    return y, (x, w, lw, lb, mb, res)


def _lnmm_bwd(d, eps, block_rows, block_n, parallel, interpret,
              residuals, g):
    x, w, lw, lb, mb, res, = residuals
    # Recompute the LN output + stats in one kernel (flash-style: no
    # (rows, d) activation saved); padded lanes of h are exact zeros.
    h, mean, rstd = _ln_pallas_fwd(x, res, lw, lb, d=d, eps=eps,
                                   block_rows=block_rows,
                                   parallel=parallel, interpret=interpret)
    # matmul grads are plain MXU work XLA already schedules optimally
    dw = jax.lax.dot_general(
        h, g, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    dmb = (jnp.sum(g.astype(jnp.float32), axis=0,
                   keepdims=True).astype(mb.dtype)
           if mb is not None else None)
    dh = jax.lax.dot_general(
        g, w, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dx, dlw, dlb = _ln_pallas_bwd(x, res, lw, lb, dh, mean, rstd, d=d,
                                  block_rows=block_rows,
                                  interpret=interpret)
    return (dx, dw,
            None if lw is None else dlw.astype(lw.dtype),
            None if lb is None else dlb.astype(lb.dtype),
            dmb,
            None if res is None else dx.astype(res.dtype))


_lnmm.defvjp(_lnmm_fwd, _lnmm_bwd)


def _lnmm_tune_key(rows, d, n, dtype, interpret):
    return (rows, d, n, str(dtype), bool(interpret))


def fused_ln_matmul(x, weight, ln_weight=None, ln_bias=None, bias=None,
                    residual=None, *, epsilon=1e-5, block_rows=None,
                    block_n=None, parallel=True, interpret=None):
    """(residual +) LayerNorm + matmul (+ bias) as one kernel launch.

    ``x`` is (rows, d), ``weight`` is (d, n); the LN output feeds the
    MXU straight from vmem instead of round-tripping through HBM.
    Returns (rows, n) in ``x.dtype`` (f32 accumulation throughout).
    The backward recomputes the LN activation from ``x`` (flash-style)
    and reuses the fused-LN backward kernel for dx/dln.

    ``block_rows``/``block_n`` default to the generator-searched choice
    when :func:`tune_ln_matmul` has cached one, else (256, 256).
    """
    if x.ndim != 2 or weight.ndim != 2:
        raise ValueError(
            f"fused_ln_matmul expects 2-D x/weight, got "
            f"{x.shape} @ {weight.shape}")
    if interpret is None:
        interpret = _interpret_default()
    rows, d = x.shape
    n = weight.shape[1]
    if block_rows is None and block_n is None:
        from . import autotune as _at
        hit = _at.cache_get("fused_ln_matmul", _lnmm_tune_key(
            rows, d, n, x.dtype, interpret)) if _at.enabled() else None
        if hit is not None:
            block_rows, block_n = int(hit[0]), int(hit[1])
            parallel = bool(hit[2])
    block_rows = 256 if block_rows is None else int(block_rows)
    block_n = 256 if block_n is None else int(block_n)
    block_rows = min(block_rows, _ceil_to(rows, 8))
    block_n = min(block_n, _ceil_to(n, _LANES))
    d_pad = _ceil_to(d, _LANES)
    rows_p = _ceil_to(rows, block_rows)
    n_pad = _ceil_to(n, block_n)

    xp = jnp.pad(x, ((0, rows_p - rows), (0, d_pad - d)))
    wp = jnp.pad(weight, ((0, d_pad - d), (0, n_pad - n)))
    lwp = lbp = mbp = rp = None
    if ln_weight is not None:
        lwp = jnp.pad(jnp.reshape(ln_weight, (1, d)),
                      ((0, 0), (0, d_pad - d)))
    if ln_bias is not None:
        lbp = jnp.pad(jnp.reshape(ln_bias, (1, d)),
                      ((0, 0), (0, d_pad - d)))
    if bias is not None:
        mbp = jnp.pad(jnp.reshape(bias, (1, n)), ((0, 0), (0, n_pad - n)))
    if residual is not None:
        rp = jnp.pad(residual, ((0, rows_p - rows), (0, d_pad - d)))
    y = _lnmm(xp, wp, lwp, lbp, mbp, rp, d, float(epsilon), block_rows,
              block_n, bool(parallel), interpret)
    return y[:rows, :n]


def ln_matmul_reference(x, weight, ln_weight=None, ln_bias=None,
                        bias=None, residual=None, epsilon=1e-5):
    """Pure-jnp reference: LN (+res) then matmul (+bias), f32 accum."""
    h = layer_norm_reference(x, ln_weight, ln_bias, residual, epsilon)
    y = jnp.dot(h, weight, preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# block-fused: matmul + bias + gelu (MLP up-projection)
# ---------------------------------------------------------------------------
def _gelu_f32(z, approximate):
    if approximate:
        inner = 0.7978845608028654 * (z + 0.044715 * z * z * z)
        return 0.5 * z * (1.0 + jnp.tanh(inner))
    return 0.5 * z * (1.0 + jax.lax.erf(z * 0.7071067811865476))


def _mbg_fwd_kernel(*refs, approximate, has_b):
    it = iter(refs)
    x_ref, w_ref = next(it), next(it)
    b_ref = next(it) if has_b else None
    y_ref, z_ref = next(it), next(it)
    acc = jax.lax.dot_general(
        x_ref[:], w_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if has_b:
        acc = acc + b_ref[:].astype(jnp.float32)
    y_ref[:] = _gelu_f32(acc, approximate).astype(y_ref.dtype)
    z_ref[:] = acc.astype(z_ref.dtype)


def _mbg_pallas_fwd(x, w, b, *, block_rows, block_n, approximate,
                    parallel, interpret):
    rows_p, k_pad = x.shape
    n_pad = w.shape[1]
    ni, nj = rows_p // block_rows, n_pad // block_n
    has_b = b is not None
    in_specs = [
        pl.BlockSpec((block_rows, k_pad), lambda i, j: (i, 0)),
        pl.BlockSpec((k_pad, block_n), lambda i, j: (0, j)),
    ]
    args = [x, w]
    if has_b:
        in_specs.append(pl.BlockSpec((1, block_n), lambda i, j: (0, j)))
        args.append(b)
    out_spec = pl.BlockSpec((block_rows, block_n), lambda i, j: (i, j))
    return pl.pallas_call(
        functools.partial(_mbg_fwd_kernel, approximate=approximate,
                          has_b=has_b),
        grid=(ni, nj),
        in_specs=in_specs,
        out_specs=[out_spec, out_spec],
        out_shape=[jax.ShapeDtypeStruct((rows_p, n_pad), x.dtype),
                   jax.ShapeDtypeStruct((rows_p, n_pad), x.dtype)],
        compiler_params=_CompilerParams(dimension_semantics=(
            "parallel" if parallel else "arbitrary", "parallel")),
        interpret=interpret,
    )(*args)


_MBG_STATICS = (3, 4, 5, 6, 7)


@functools.partial(jax.custom_vjp, nondiff_argnums=_MBG_STATICS)
def _mbg(x, w, b, approximate, block_rows, block_n, parallel, interpret):
    y, _ = _mbg_pallas_fwd(x, w, b, block_rows=block_rows,
                           block_n=block_n, approximate=approximate,
                           parallel=parallel, interpret=interpret)
    return y


def _mbg_fwd(x, w, b, approximate, block_rows, block_n, parallel,
             interpret):
    y, z = _mbg_pallas_fwd(x, w, b, block_rows=block_rows,
                           block_n=block_n, approximate=approximate,
                           parallel=parallel, interpret=interpret)
    return y, (x, w, b, z)


def _mbg_bwd(approximate, block_rows, block_n, parallel, interpret,
             residuals, g):
    x, w, b, z = residuals
    # dz from the saved pre-activation (the exact gelu' the primal used)
    _, pull = jax.vjp(lambda t: _gelu_f32(t, approximate),
                      z.astype(jnp.float32))
    dz = pull(g.astype(jnp.float32))[0].astype(x.dtype)
    dx = jax.lax.dot_general(
        dz, w, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dw = jax.lax.dot_general(
        x, dz, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    db = (jnp.sum(dz.astype(jnp.float32), axis=0,
                  keepdims=True).astype(b.dtype)
          if b is not None else None)
    return dx, dw, db


_mbg.defvjp(_mbg_fwd, _mbg_bwd)


def _mbg_tune_key(rows, k, n, dtype, approximate, interpret):
    return (rows, k, n, str(dtype), bool(approximate), bool(interpret))


def fused_matmul_bias_gelu(x, weight, bias=None, *, approximate=True,
                           block_rows=None, block_n=None, parallel=True,
                           interpret=None):
    """``gelu(x @ weight + bias)`` with the activation applied on the
    MXU accumulator — the transformer MLP up-projection as one launch.

    ``x`` is (rows, k), ``weight`` is (k, n); returns (rows, n) in
    ``x.dtype``.  The pre-activation is saved for the backward (one
    extra (rows, n) write beats re-running the matmul).
    """
    if x.ndim != 2 or weight.ndim != 2:
        raise ValueError(
            f"fused_matmul_bias_gelu expects 2-D x/weight, got "
            f"{x.shape} @ {weight.shape}")
    if interpret is None:
        interpret = _interpret_default()
    rows, k = x.shape
    n = weight.shape[1]
    if block_rows is None and block_n is None:
        from . import autotune as _at
        hit = _at.cache_get("fused_matmul_bias_gelu", _mbg_tune_key(
            rows, k, n, x.dtype, approximate,
            interpret)) if _at.enabled() else None
        if hit is not None:
            block_rows, block_n = int(hit[0]), int(hit[1])
            parallel = bool(hit[2])
    block_rows = 256 if block_rows is None else int(block_rows)
    block_n = 256 if block_n is None else int(block_n)
    block_rows = min(block_rows, _ceil_to(rows, 8))
    block_n = min(block_n, _ceil_to(n, _LANES))
    k_pad = _ceil_to(k, _LANES)
    rows_p = _ceil_to(rows, block_rows)
    n_pad = _ceil_to(n, block_n)

    xp = jnp.pad(x, ((0, rows_p - rows), (0, k_pad - k)))
    wp = jnp.pad(weight, ((0, k_pad - k), (0, n_pad - n)))
    bp = (jnp.pad(jnp.reshape(bias, (1, n)), ((0, 0), (0, n_pad - n)))
          if bias is not None else None)
    y = _mbg(xp, wp, bp, bool(approximate), block_rows, block_n,
             bool(parallel), interpret)
    return y[:rows, :n]


def matmul_bias_gelu_reference(x, weight, bias=None, approximate=True):
    """Pure-jnp reference: matmul (+bias) then gelu, f32 accum."""
    z = jnp.dot(x, weight, preferred_element_type=jnp.float32)
    if bias is not None:
        z = z + bias.astype(jnp.float32)
    return _gelu_f32(z, approximate).astype(x.dtype)


# ---------------------------------------------------------------------------
# block-fused: attention (qkv-matmul + scale + softmax + pv-matmul)
# ---------------------------------------------------------------------------
def fused_attention_block(q, k, v, *, causal=False, sm_scale=None,
                          block_q=None, block_k=None, interpret=None):
    """The attention score/softmax/weighted-sum cluster as one flash
    kernel launch ((B, H, S, D) layout; see :func:`pallas_ops.mha`).
    Exists so the fusion pass and bench address the attention-block
    pattern through the same module as the other block kernels."""
    from .pallas_ops import mha
    return mha(q, k, v, causal=causal, sm_scale=sm_scale,
               block_q=block_q, block_k=block_k, interpret=interpret)


def attention_block_reference(q, k, v, *, causal=False, sm_scale=None):
    """Pure-jnp reference ((B, H, S, D) layout, f32 softmax)."""
    from .pallas_ops import mha_reference
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)


# ---------------------------------------------------------------------------
# generator-backed tuners for the block kernels
# ---------------------------------------------------------------------------
def _block_axes(rows, n):
    """Candidate axes for a (rows × n)-tiled block kernel: the generator
    emits (block_rows, block_n, parallel) tuples from the cluster shape
    instead of reading a static table."""
    return [("tile", min(rows, 1024), 8), ("tile", min(n, 1024), _LANES),
            ("choice", (1, 0))]


def _block_cost_fn(rows, d, n, itemsize):
    """Cost estimate for the (rows, d) @ (d, n) block kernels.  A
    per-launch overhead term breaks the roofline tie between tile
    sizes (same total work) so the generator's ordering prefers fewer,
    larger launches within the vmem budget."""
    d_pad = _ceil_to(d, _LANES)
    flops = 2.0 * rows * d * n
    bytes_ = float(rows * d + d * n + rows * n) * itemsize

    def cost(cfg):
        br = min(int(cfg[0]), _ceil_to(rows, 8))
        bn = min(int(cfg[1]), _ceil_to(n, _LANES))
        n_launch = (_ceil_to(rows, br) // br) * (_ceil_to(n, bn) // bn)
        vmem = (br * d_pad * (itemsize + _F32)   # x tile + f32 copy
                + d_pad * bn * itemsize          # weight tile
                + br * bn * (itemsize + _F32)    # out tile + accumulator
                + 3 * d_pad * _F32 + 2 * br * _F32)
        return {"flops": flops,
                "bytes": bytes_ + n_launch * 16384.0,
                "vmem_bytes": vmem,
                "mxu_underfill": br < 8 or bn < _LANES}
    return cost


def tune_ln_matmul(x, weight, ln_weight=None, ln_bias=None, bias=None,
                   residual=None, *, epsilon=1e-5, interpret=None):
    """Generate + search launch configs for :func:`fused_ln_matmul` at
    this (rows, d, n, dtype) and cache the winner.  Unlike the PR 8
    tuners there is no candidate table: :func:`autotune.
    generate_candidates` emits the (block_rows, block_n, parallel)
    space from the cluster shape and prunes it through the cost model
    before timing.  Returns (best, timings)."""
    from . import autotune as _at

    if interpret is None:
        interpret = _interpret_default()
    rows, d = x.shape
    n = weight.shape[1]
    cost = _block_cost_fn(rows, d, n, x.dtype.itemsize)
    cands = _at.generate_candidates(_block_axes(rows, n), cost,
                                    max_candidates=8)
    state = {"x": x}

    def run(cfg):
        out = fused_ln_matmul(state["x"], weight, ln_weight, ln_bias,
                              bias, residual, epsilon=epsilon,
                              block_rows=cfg[0], block_n=cfg[1],
                              parallel=bool(cfg[2]), interpret=interpret)
        float(jnp.sum(out.astype(jnp.float32)))

    best, timings = _at.search(
        "fused_ln_matmul",
        _lnmm_tune_key(rows, d, n, x.dtype, interpret),
        run, cands, cost=cost)
    _at.set_enabled(True)
    return best, timings


def tune_matmul_bias_gelu(x, weight, bias=None, *, approximate=True,
                          interpret=None):
    """Generate + search launch configs for
    :func:`fused_matmul_bias_gelu` (see :func:`tune_ln_matmul`)."""
    from . import autotune as _at

    if interpret is None:
        interpret = _interpret_default()
    rows, k = x.shape
    n = weight.shape[1]
    cost = _block_cost_fn(rows, k, n, x.dtype.itemsize)
    cands = _at.generate_candidates(_block_axes(rows, n), cost,
                                    max_candidates=8)

    def run(cfg):
        out = fused_matmul_bias_gelu(
            x, weight, bias, approximate=approximate, block_rows=cfg[0],
            block_n=cfg[1], parallel=bool(cfg[2]), interpret=interpret)
        float(jnp.sum(out.astype(jnp.float32)))

    best, timings = _at.search(
        "fused_matmul_bias_gelu",
        _mbg_tune_key(rows, k, n, x.dtype, approximate, interpret),
        run, cands, cost=cost)
    _at.set_enabled(True)
    return best, timings
