"""Functional op surface.

One flat namespace mirroring ``python/paddle/tensor/`` — creation, math,
manipulation, logic, linalg, search — re-exported at the package top level
(`paddle_tpu.add` etc.) and installed as Tensor methods
(`x.add(y)`, `x + y`), matching the reference's monkey-patched tensor
method surface (``python/paddle/tensor/__init__.py``).
"""
from . import creation, math, manipulation, logic, linalg, search  # noqa: F401
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403

# star-export only op names, NOT the submodule objects — otherwise
# `paddle_tpu.linalg`/`paddle_tpu.math` would shadow the real top-level
# namespace modules of the same name
__all__ = (creation.__all__ + math.__all__ + manipulation.__all__ +
           logic.__all__ + linalg.__all__ + search.__all__)

from ..tensor import Tensor
from . import math as _m, manipulation as _mp, logic as _lg, linalg as _la, \
    search as _s, creation as _c

_METHOD_SOURCES = [_m, _mp, _lg, _la, _s]

# names that become Tensor methods (subset of module functions whose first
# arg is the tensor)
_METHODS = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "maximum", "minimum", "exp", "expm1", "log", "log2",
    "log10", "log1p", "sqrt", "rsqrt", "square", "abs", "sign", "floor",
    "ceil", "round", "trunc", "frac", "reciprocal", "neg", "sin", "cos",
    "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh", "acosh",
    "atanh", "atan2", "erf", "erfinv", "lgamma", "digamma", "logit",
    "sigmoid", "sum", "mean", "max", "min", "prod", "amax", "amin",
    "nansum", "nanmean", "cumsum", "cumprod", "logsumexp", "logcumsumexp",
    "clip", "isnan", "isinf", "isfinite", "nan_to_num", "all", "any",
    "heaviside", "kron", "trace", "diagonal", "angle", "conj", "real",
    "imag", "lerp", "median", "nanmedian", "quantile", "std", "var",
    "count_nonzero", "inner", "outer", "scale", "lcm", "gcd",
    "add_", "subtract_", "multiply_", "divide_", "clip_", "scale_",
    "floor_", "ceil_", "exp_", "sqrt_", "rsqrt_", "reciprocal_", "round_",
    "sigmoid_", "tanh_",
    # manipulation
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "transpose", "moveaxis", "concat", "split", "chunk",
    "tile", "expand", "expand_as", "broadcast_to", "gather", "gather_nd",
    "scatter", "scatter_", "scatter_nd_add", "index_select", "index_sample",
    "index_add", "index_add_", "index_put", "index_put_", "take_along_axis", "put_along_axis", "roll",
    "flip", "rot90", "unbind", "repeat_interleave", "slice", "strided_slice",
    "pad", "masked_fill", "masked_select", "masked_scatter", "where",
    "unflatten", "unfold", "tolist", "numel", "swapaxes", "tensor_split",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_xor", "logical_not", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not",
    # linalg
    "matmul", "mm", "bmm", "dot", "mv", "dist", "norm", "cholesky",
    "inverse", "det", "slogdet", "solve", "matrix_power", "cross",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
    "nonzero", "unique", "bincount", "histogram",
    # creation (tensor-first only)
    "tril", "triu", "diag", "bernoulli", "normal_", "uniform_",
    "exponential_", "zeros_like", "ones_like", "full_like",
]


def _install_tensor_methods():
    for name in _METHODS:
        fn = None
        for mod in _METHOD_SOURCES + [_c]:
            fn = getattr(mod, name, None)
            if fn is not None:
                break
        if fn is None:
            raise RuntimeError(f"op {name} not found for Tensor method binding")
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    # dunder operators
    import jax.numpy as jnp
    from .op_utils import binary as _binary, unary as _unary
    # forward binary dunders bind the op directly (no lambda frame —
    # this is the eager dispatch floor, see bench_eager.py)
    Tensor.__add__ = _m.add
    Tensor.__radd__ = lambda s, o: _m.add(o, s)
    Tensor.__sub__ = _m.subtract
    Tensor.__rsub__ = lambda s, o: _m.subtract(o, s)
    Tensor.__mul__ = _m.multiply
    Tensor.__rmul__ = lambda s, o: _m.multiply(o, s)
    Tensor.__truediv__ = _m.divide
    Tensor.__rtruediv__ = lambda s, o: _m.divide(o, s)
    Tensor.__floordiv__ = lambda s, o: _m.floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: _m.floor_divide(o, s)
    Tensor.__mod__ = lambda s, o: _m.mod(s, o)
    Tensor.__rmod__ = lambda s, o: _m.mod(o, s)
    Tensor.__pow__ = lambda s, o: _m.pow(s, o)
    Tensor.__rpow__ = lambda s, o: _m.pow(o, s)
    Tensor.__neg__ = lambda s: _m.neg(s)
    Tensor.__abs__ = lambda s: _m.abs(s)
    Tensor.__matmul__ = lambda s, o: _la.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: _la.matmul(o, s)
    Tensor.__eq__ = lambda s, o: _lg.equal(s, o)
    Tensor.__ne__ = lambda s, o: _lg.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: _lg.less_than(s, o)
    Tensor.__le__ = lambda s, o: _lg.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: _lg.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: _lg.greater_equal(s, o)
    Tensor.__and__ = lambda s, o: _lg.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: _lg.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: _lg.bitwise_xor(s, o)
    Tensor.__invert__ = lambda s: _lg.bitwise_not(s)
    Tensor.__lshift__ = lambda s, o: _lg.bitwise_left_shift(s, o)
    Tensor.__rshift__ = lambda s, o: _lg.bitwise_right_shift(s, o)
    # hash must survive __eq__ override
    Tensor.__hash__ = lambda s: id(s)


_install_tensor_methods()
