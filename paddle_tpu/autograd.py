"""Eager autograd engine.

TPU-native re-design of the reference's dygraph autograd:
 - grad-node graph + queue-based backward walk:
   ``paddle/fluid/eager/backward.cc:104 RunBackward``,
   ``paddle/fluid/eager/grad_node_info.h:168 GradNodeBase``
 - per-op capture: the reference *code-generates* a GradNode class per op
   (``eager/auto_code_generator/generator/eager_gen.py:960``); here a single
   generic tape node captures ``jax.vjp`` of the op's pure function — JAX's
   tracing IS the code generator, so there is nothing to generate.

Key property: ``jax.vjp(fn, *primals)`` runs the forward exactly once on
device and returns a host-side closure over the residuals, so eager mode pays
no double-compute for recording gradients. Under ``to_static``/jit tracing the
tape is bypassed (`functional_guard`) and gradients come from functional
``jax.grad`` over the whole step — the fast path.
"""
from __future__ import annotations

import contextlib
import functools
import threading
import weakref
from collections import deque

import numpy as np
import jax

from .framework import flags as _flags

__all__ = [
    "no_grad", "enable_grad", "set_grad_enabled", "is_grad_enabled",
    "backward", "grad", "PyLayer", "PyLayerContext",
    "saved_tensors_hooks", "jacobian", "hessian", "Jacobian", "Hessian",
]

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "enabled", True)


def _set_enabled(v: bool):
    _state.enabled = v


def in_functional_mode() -> bool:
    """True while tracing a functional (jit) program — tape disabled."""
    return getattr(_state, "functional", 0) > 0


@contextlib.contextmanager
def functional_guard():
    _state.functional = getattr(_state, "functional", 0) + 1
    try:
        yield
    finally:
        _state.functional -= 1


class _GradCtx:
    """Context manager / decorator toggling grad recording (paddle.no_grad)."""

    def __init__(self, enable: bool):
        self._enable = enable

    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_enabled(self._enable)
        return self

    def __exit__(self, *exc):
        _set_enabled(self._prev)
        return False

    def __call__(self, fn):
        enable = self._enable

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradCtx(enable):
                return fn(*args, **kwargs)
        return wrapper


class no_grad(_GradCtx):
    def __init__(self):
        super().__init__(False)


class enable_grad(_GradCtx):
    def __init__(self):
        super().__init__(True)


class set_grad_enabled(_GradCtx):
    def __init__(self, mode: bool):
        super().__init__(bool(mode))


class Node:
    """One recorded op on the tape.

    inputs:  Tensors the op consumed (strong refs keep the graph alive as
             long as any output lives — same lifetime rule as the
             reference's shared_ptr grad-node chain).
    vjp_fn:  pullback closure. Funnel-recorded ops build it LAZILY — the
             forward only runs the bare op and stashes (fn, input arrays);
             ``jax.vjp`` is traced at backward time via :meth:`pullback`.
             The reference pays a whole codegen subsystem to keep eager
             dispatch cheap (``paddle/fluid/eager/auto_code_generator/``);
             deferring the trace is the tape's analog — forward dispatch
             drops from one jax trace per op to one jnp call per op
             (bench_eager.py measures it). PyLayer / functional_call nodes
             still pass an explicit vjp_fn.
    outputs: weakrefs to produced Tensors (to locate incoming cotangents).
    """

    __slots__ = ("inputs", "vjp_fn", "fn", "datas", "out_refs", "out_avals",
                 "name", "_hooks", "_released", "_unpack", "__weakref__")

    def __init__(self, inputs, vjp_fn, outputs, name="", fn=None,
                 datas=None):
        self.inputs = list(inputs)
        self.vjp_fn = vjp_fn
        self.fn = fn
        self.datas = datas
        self.out_refs = [weakref.ref(t) for t in outputs]
        self.out_avals = [(t.shape, t._data.dtype) for t in outputs]
        self.name = name
        self._hooks = None
        self._released = False
        self._unpack = None

    def pullback(self, cot):
        if self._released:
            raise RuntimeError(
                "Trying to backward through the graph a second time; "
                "set retain_graph=True if you need to.")
        if self.vjp_fn is None:
            # deferred trace: input arrays were captured at record time, so
            # later in-place rebinds of the input Tensors don't corrupt it
            datas = self.datas
            if self._unpack is not None:
                datas = tuple(_unpack_saved(self._unpack, p) for p in datas)
            _, self.vjp_fn = jax.vjp(self.fn, *datas)
        return self.vjp_fn(cot)

    def release(self):
        self.vjp_fn = None
        self.fn = None
        self.datas = None
        self.inputs = []
        self._released = True


# static-graph recorder hook; installed by paddle_tpu.static.graph so the
# one op funnel serves both dygraph (execute + tape) and static (record node)
_static_recorder = None
_STATIC_SENTINEL = None

_node_new = Node.__new__
_flag_values = _flags._values  # direct dict ref for the per-op hot path
_wref = weakref.ref

# single-output fast path: op_utils registers its (_wrap_single,
# _fast_tensor) pair so record() can skip the list-of-outputs protocol
# — no [t] alloc, no comprehensions — for the overwhelmingly common
# one-output op (the per-op dispatch floor bench_eager.py tracks)
_single_wrap_fn = None
_single_ctor = None


def _register_single_wrap(wrap, ctor):
    global _single_wrap_fn, _single_ctor
    _single_wrap_fn, _single_ctor = wrap, ctor


def _repoint_out_ref(node, idx, ref):
    refs = node.out_refs
    if type(refs) is tuple:  # single-output fast path stores a tuple
        node.out_refs = refs[:idx] + (ref,) + refs[idx + 1:]
    else:
        refs[idx] = ref


def _hooks_stack():
    """Per-thread hook stack — a hooks context in one thread must not
    pack tensors recorded concurrently by other threads (all other
    autograd mode state lives on ``_state`` for the same reason)."""
    st = _state.__dict__
    stack = st.get("saved_hooks")
    if stack is None:
        stack = st["saved_hooks"] = []
    return stack


class saved_tensors_hooks:
    """Pack/unpack hooks over tensors saved for backward (ref
    ``python/paddle/autograd/saved_tensors_hooks.py:20``): every array
    the tape captures for a node's deferred vjp is passed (as a Tensor)
    through ``pack_hook`` at record time, and ``unpack_hook`` rebuilds
    it at backward time — the offload-to-CPU/disk extension point."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _hooks_stack().append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _hooks_stack().pop()
        return False


def _pack_saved(node):
    from .tensor import Tensor
    pack, unpack = _hooks_stack()[-1]
    node.datas = tuple(pack(Tensor(d)) for d in node.datas)
    node._unpack = unpack


def _unpack_saved(unpack, packed):
    t = unpack(packed)
    return t._data if hasattr(t, "_data") else t


def rebind_inplace(x, out):
    """Make ``x`` become ``out`` in place (paddle's ``op_`` variants):
    rebind data + tape linkage, then repoint the producing node's output
    ref at the surviving tensor so backward finds cotangents under it.

    When the op was recorded for grad and ``x`` is among its inputs, the
    pre-inplace producer chain must survive the rebind: a lightweight
    proxy tensor takes ``x``'s place in the node's inputs (and in the
    old producer's out_refs), so backward still reaches everything
    upstream of the overwritten value. A grad-requiring LEAF cannot be
    rebound this way — same rule as the reference
    (``paddle/fluid/eager/api/utils/tensor_utils.cc`` inplace check:
    "Leaf Var that doesn't stop gradient can't use inplace strategy")."""
    node = out._node
    if node is not None:
        # ONE proxy shared by every occurrence of x in the inputs: a
        # proxy per occurrence would fight over the producer's single
        # out_ref and silently drop all but the last cotangent. A
        # stop-gradient leaf gets a constant proxy (_node=None) too —
        # leaving x itself in inputs would make the node consume its
        # own output after the rebind and deadlock the backward walk.
        proxy = None
        for j, t in enumerate(node.inputs):
            if t is x:
                if proxy is None:
                    if x._node is None and not x.stop_gradient:
                        raise RuntimeError(
                            "Leaf Tensor that doesn't stop gradient can't "
                            "use inplace strategy; detach() it or wrap the "
                            "update in no_grad()")
                    proxy = _single_ctor(x._data, not x.stop_gradient)
                    if x._node is not None:
                        proxy._node = x._node
                        proxy._out_idx = x._out_idx
                        _repoint_out_ref(x._node, x._out_idx, _wref(proxy))
                node.inputs[j] = proxy  # strong ref keeps proxy alive
    x._data = out._data
    x._node = out._node
    x._out_idx = out._out_idx
    x.stop_gradient = out.stop_gradient and x.stop_gradient
    if node is not None:
        _repoint_out_ref(node, x._out_idx, _wref(x))
    return x

# op observers: every funnel-recorded op reports (name, inputs, outputs).
# Serves amp.debugging operator-stats / tensor-checker tooling (ref
# ``python/paddle/amp/debugging.py``); empty-list check keeps the hot
# path free when unused.
_op_observers: list = []


def add_op_observer(fn):
    """fn(op_name, input_tensors, output_tensors) on every recorded op."""
    _op_observers.append(fn)
    return fn


def remove_op_observer(fn):
    try:
        _op_observers.remove(fn)
    except ValueError:
        pass


def record(fn, tensors, outputs_wrap, name=""):
    """Run `fn(*datas)` with optional tape capture.

    fn: pure function over raw jax arrays returning array or tuple of arrays.
    tensors: Tensor inputs in fn arg order.
    outputs_wrap: callable(raw_out, requires_grad) -> (tensors_list, result)
    """
    if _static_recorder is not None:
        res = _static_recorder(fn, tensors, outputs_wrap, name)
        if res is not _STATIC_SENTINEL:
            return res
    # inlined is_grad_enabled()/in_functional_mode(): the per-op eager
    # path is the framework's dispatch floor (bench_eager.py tracks it),
    # so thread-local state is read via one __dict__ lookup each; the
    # 1/2-arity cases (the whole elementwise funnel) skip the generic
    # tuple build + stop_gradient loop
    st = _state.__dict__
    n = len(tensors)
    if n == 2:
        a, b = tensors
        datas = (a._data, b._data)
        needs_grad = not (a.stop_gradient and b.stop_gradient)
    elif n == 1:
        a = tensors[0]
        datas = (a._data,)
        needs_grad = not a.stop_gradient
    else:
        datas = tuple(t._data for t in tensors)
        needs_grad = any(not t.stop_gradient for t in tensors)
    if needs_grad and (not st.get("enabled", True) or st.get("functional", 0)):
        needs_grad = False
    raw = fn(*datas)
    if outputs_wrap is _single_wrap_fn:
        t = _single_ctor(raw, needs_grad)
        if needs_grad:
            node = _node_new(Node)
            node.inputs = tensors  # callers pass fresh lists; alias
            node.vjp_fn = None
            node.fn = fn
            node.datas = datas
            node.out_refs = (_wref(t),)
            d = t._data
            node.out_avals = ((d.shape, d.dtype),)
            node.name = name
            node._hooks = None
            node._released = False
            node._unpack = None
            if st.get("saved_hooks"):
                _pack_saved(node)
            t._node = node  # _out_idx is already 0 from the ctor
        if _flag_values.get("check_nan_inf"):
            _check_nan_inf((t,), name)
        if _op_observers:
            for ob in list(_op_observers):
                ob(name, tensors, (t,))
        return t
    out_tensors, result = outputs_wrap(raw, needs_grad)
    if needs_grad:
        node = _node_new(Node)
        node.inputs = tensors  # callers pass fresh lists; alias, no copy
        node.vjp_fn = None
        node.fn = fn
        node.datas = datas
        node.out_refs = [weakref.ref(t) for t in out_tensors]
        node.out_avals = [(t._data.shape, t._data.dtype)
                          for t in out_tensors]
        node.name = name
        node._hooks = None
        node._released = False
        node._unpack = None
        if st.get("saved_hooks"):
            _pack_saved(node)
        for i, t in enumerate(out_tensors):
            t._node = node
            t._out_idx = i
    if _flag_values.get("check_nan_inf"):
        _check_nan_inf(out_tensors, name)
    if _op_observers:
        for ob in list(_op_observers):
            ob(name, tensors, out_tensors)
    return result


def _check_nan_inf(tensors, name):
    """FLAGS_check_nan_inf analog (ref: paddle/fluid/eager/nan_inf_utils.cc)."""
    import jax.numpy as jnp
    for t in tensors:
        d = t._data
        if isinstance(d, jax.core.Tracer):
            continue
        if np.issubdtype(np.dtype(d.dtype), np.floating) or d.dtype == jnp.bfloat16:
            # debug-mode op-output audit: concrete (non-tracer)
            # values only, and raising eagerly is the feature
            # tpu-lint: disable=TPU017
            if bool(jnp.any(~jnp.isfinite(d))):
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op '{name or 'unknown'}'")


def _zero_cot(shape, dt):
    if np.issubdtype(np.dtype(dt), np.integer) or np.dtype(dt) == np.bool_:
        return np.zeros(shape, dtype=jax.dtypes.float0)
    import jax.numpy as jnp
    return jnp.zeros(shape, dtype=dt)


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False):
    """Queue-based reverse walk over the tape.

    Mirrors ``egr::RunBackward`` (``backward.cc:104``): seed cotangents,
    count consumer edges per node, process nodes whose consumers are all
    done, accumulate into leaf ``.grad``.

    ``create_graph=True`` (higher-order, ref ``paddle/fluid/prim/`` +
    ``incubate/autograd/primapi.py:220``): each node's pullback is
    re-executed THROUGH the tape (:func:`_taped_pullback`) and cotangent
    accumulation uses taped adds, so the produced gradients carry their
    own tape and can be differentiated again. Implies retain_graph.
    """
    import jax.numpy as jnp
    from .tensor import Tensor

    if create_graph:
        retain_graph = True
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangent accumulator keyed by tensor identity
    cots: dict[int, object] = {}
    keep: dict[int, object] = {}  # keep tensors alive during walk

    def accum(t, g):
        if g is None or isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
            return
        k = id(t)
        keep[k] = t
        if k in cots:
            cots[k] = cots[k] + g  # taped add when both are Tensors
        else:
            cots[k] = g

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t._node is None and t.stop_gradient:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones_like(t._data)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if create_graph:
            g = Tensor(g, stop_gradient=True)
        if t._node is not None:
            accum(t, g)
            roots.append(t._node)
        else:
            # root IS a leaf: its seed gradient goes straight to .grad
            _leaf_accum(t, g)

    # reachable node set
    reach: set[int] = set()
    nodes: dict[int, Node] = {}
    stack = list(roots)
    while stack:
        n = stack.pop()
        if id(n) in reach:
            continue
        reach.add(id(n))
        nodes[id(n)] = n
        for t in n.inputs:
            if t._node is not None:
                stack.append(t._node)

    # consumer edge counts
    pending: dict[int, int] = {k: 0 for k in reach}
    for n in nodes.values():
        seen_producers = set()
        for t in n.inputs:
            p = t._node
            if p is not None and id(p) in reach:
                # one edge per (consumer, input-tensor) occurrence
                pending[id(p)] += 1
            del p
        del seen_producers

    # A node is initially ready iff no reachable node consumes its outputs.
    ready = deque(n for k, n in nodes.items() if pending[k] == 0)
    processed = set()
    while ready:
        n = ready.popleft()
        if id(n) in processed:
            continue
        processed.add(id(n))
        # gather cotangents for this node's outputs
        out_cots = []
        for ref, (shape, dt) in zip(n.out_refs, n.out_avals):
            t = ref()
            g = cots.pop(id(t), None) if t is not None else None
            if g is None:
                g = _zero_cot(shape, dt)
                if create_graph and not (isinstance(g, np.ndarray)
                                         and g.dtype == jax.dtypes.float0):
                    from .tensor import Tensor as _T
                    g = _T(g, stop_gradient=True)
            out_cots.append(g)
        if create_graph:
            in_grads = _taped_pullback(n, out_cots)
        else:
            cot_in = out_cots[0] if len(out_cots) == 1 else tuple(out_cots)
            in_grads = n.pullback(cot_in)
        if n._hooks:
            in_grads = list(in_grads)
            for i, h in n._hooks:
                in_grads[i] = h(in_grads[i])
        for t, g in zip(n.inputs, in_grads):
            # a float0 cotangent (int-dtype input) carries no gradient, but
            # the consumer edge must still be counted down or the producer
            # node never becomes ready and valid sibling paths are dropped
            is_f0 = isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0
            if t._node is None:
                if not t.stop_gradient and not is_f0:
                    _leaf_accum(t, g)
            else:
                if not is_f0:
                    accum(t, g)
                p = t._node
                if id(p) in reach:
                    pending[id(p)] -= 1
                    if pending[id(p)] == 0:
                        ready.append(p)
        if not retain_graph:
            n.release()


def _taped_pullback(n, out_cots):
    """create_graph backward-of-backward: run the node's vjp THROUGH the
    tape so the produced gradients are themselves differentiable.

    The pullback is the pure function ``(cot, *float_inputs) ->
    float_input_grads`` (re-traced from the node's stored ``fn``);
    recording it via :func:`record` gives the grads tape edges back to
    both the cotangents and the node's input tensors. Nodes built from an
    opaque ``vjp_fn`` (PyLayer / functional_call) cannot be re-traced —
    their grads come back as constants (the graph stops there, like a
    non-differentiable custom backward in the reference).
    """
    from .tensor import Tensor
    multi = len(out_cots) > 1

    if n.fn is None or n.datas is None:
        if n._released:
            raise RuntimeError(
                "Trying to backward through the graph a second time; "
                "set retain_graph=True if you need to.")
        raw_cots = [c._data if isinstance(c, Tensor) else c
                    for c in out_cots]
        raw = n.vjp_fn(tuple(raw_cots) if multi else raw_cots[0])
        return [Tensor(g, stop_gradient=True)
                if not (isinstance(g, np.ndarray)
                        and g.dtype == jax.dtypes.float0) else g
                for g in raw]

    # differentiable slots: float cotangents + float node inputs
    slots: list = []          # Tensors handed to record()
    cot_template: list = []   # per-cot: slot index or the constant itself
    for c in out_cots:
        if isinstance(c, Tensor):
            cot_template.append(len(slots))
            slots.append(c)
        else:
            cot_template.append(c)  # float0 constant for int outputs
    fn, datas = n.fn, n.datas
    if n._unpack is not None:  # saved_tensors_hooks pack/unpack
        datas = tuple(_unpack_saved(n._unpack, p) for p in datas)

    def _is_float(a):
        import jax.numpy as jnp
        return (np.issubdtype(np.dtype(a.dtype), np.floating)
                or a.dtype == jnp.bfloat16)

    float_in = [i for i, d in enumerate(datas) if _is_float(d)]
    base = len(slots)
    slots.extend(n.inputs[i] for i in float_in)

    def pb(*arrs):
        cots = [arrs[s] if isinstance(s, int) else s for s in cot_template]
        ds = list(datas)
        for j, i in enumerate(float_in):
            ds[i] = arrs[base + j]
        primal, vjp = jax.vjp(fn, *ds)
        # cotangent structure must mirror fn's own output tree (some op
        # fns return 1-tuples even for single-output nodes)
        cot = tuple(cots) if isinstance(primal, (tuple, list)) else cots[0]
        gin = vjp(cot)
        gout = tuple(gin[i] for i in float_in)
        # single-output nodes carry a bare array (tape cot_in contract)
        return gout[0] if len(gout) == 1 else gout

    def wrap(raw, req):
        raws = raw if isinstance(raw, tuple) else (raw,)
        ts = [Tensor(r, stop_gradient=not req) for r in raws]
        return ts, ts

    grads_f = record(pb, slots, wrap, name=(n.name or "op") + "_grad")
    out = []
    it = iter(grads_f)
    for i, d in enumerate(datas):
        if i in set(float_in):
            out.append(next(it))
        else:
            out.append(np.zeros(d.shape, dtype=jax.dtypes.float0))
    return out


def _leaf_accum(t, g):
    import jax.numpy as jnp
    from .tensor import Tensor
    capture = getattr(_state, "leaf_capture", None)
    if capture is not None:
        # scoped backward (paddle.grad): only capture requested leaves,
        # never touch .grad of anything else
        table, allowed = capture
        if id(t) in allowed:
            prev = table.get(id(t))
            table[id(t)] = g if prev is None else prev + g
        return
    if isinstance(g, Tensor):
        # create_graph backward: keep the taped gradient as .grad so the
        # user can differentiate through it
        t._grad = g if t._grad is None else t._grad + g
    else:
        g = jnp.asarray(g)
        if g.dtype != t._data.dtype:
            g = g.astype(t._data.dtype)
        if t._grad is None:
            t._grad = Tensor(g, stop_gradient=True)
        else:
            t._grad._data = t._grad._data + g
    if t._grad_hooks:
        for h in t._grad_hooks.values():
            out = h(t._grad)
            if out is not None:
                t._grad = out


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """``paddle.grad`` equivalent: returns grads of `outputs` w.r.t `inputs`
    without touching ``.grad`` accumulators.

    Implemented as a scoped backward: leaf accumulation is redirected to a
    side table covering ONLY `inputs`, so no tensor's ``.grad`` (including
    model parameters reachable from `outputs`) is touched.

    ``create_graph=True`` runs the backward pass THROUGH the tape
    (:func:`_taped_pullback`): the returned grads carry their own graph
    and can be fed back into :func:`grad` for second/higher derivatives
    (ref ``python/paddle/incubate/autograd/primapi.py:220`` double-grad).
    """
    from .tensor import Tensor
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    sg = [(t, t.stop_gradient) for t in inputs]
    table: dict[int, object] = {}
    _state.leaf_capture = (table, {id(t) for t in inputs})
    try:
        for t in inputs:
            t.stop_gradient = False
        backward(outputs, grad_tensors=grad_outputs,
                 retain_graph=bool(retain_graph) or create_graph,
                 create_graph=create_graph)
        results = []
        for t in inputs:
            g = table.get(id(t))
            if g is None:
                if not allow_unused:
                    raise RuntimeError(
                        "One of the differentiated tensors appears unused; "
                        "pass allow_unused=True to return None for it.")
                results.append(None)
            elif isinstance(g, Tensor):
                results.append(g)  # create_graph: keep the taped grad
            else:
                results.append(Tensor(g, stop_gradient=True))
        return results
    finally:
        _state.leaf_capture = None
        for t, s in sg:
            t.stop_gradient = s


class PyLayerContext:
    """Saved-tensor context for custom ops (ref:
    ``paddle/fluid/eager/pylayer``, python ``paddle.autograd.PyLayer``)."""

    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        # saved tensors route through active saved_tensors_hooks, same
        # contract as the funnel tape (ref saved_tensors_hooks.py:30)
        if _hooks_stack():
            pack, unpack = _hooks_stack()[-1]
            self._saved_packed = tuple(pack(t) for t in tensors)
            self._saved_unpack = unpack
            self._saved = None
        else:
            self._saved = tensors
            self._saved_unpack = None

    def _restore_saved(self):
        if getattr(self, "_saved_unpack", None) is not None:
            self._saved = tuple(self._saved_unpack(p)
                                for p in self._saved_packed)
            self._saved_unpack = None
        return self._saved

    @property
    def saved_tensor(self):
        return self._restore_saved()

    # paddle also exposes it as a method
    def saved_tensors(self):
        return self._restore_saved()


class PyLayer:
    """User-defined differentiable op with explicit forward/backward.

    Subclass and define ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    as staticmethods operating on Tensors, then call ``MyOp.apply(...)``.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from .tensor import Tensor
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (list, tuple))
        outs = list(out) if multi else [out]
        needs = (is_grad_enabled() and not in_functional_mode()
                 and any(not t.stop_gradient for t in tensor_args))
        if needs:
            def vjp_fn(cot):
                cots = list(cot) if multi else [cot]
                cot_tensors = [Tensor(c, stop_gradient=True) for c in cots]
                with no_grad():
                    gin = cls.backward(ctx, *cot_tensors)
                if not isinstance(gin, (list, tuple)):
                    gin = (gin,)
                return tuple(
                    (g._data if isinstance(g, Tensor) else g) if g is not None
                    else np.zeros(t.shape, dtype=jax.dtypes.float0)
                    for g, t in zip(gin, tensor_args))

            for t in outs:
                t.stop_gradient = False
            node = Node(tensor_args, vjp_fn, outs, name=cls.__name__)
            for i, t in enumerate(outs):
                t._node = node
                t._out_idx = i
        return out if multi else outs[0]


from .autograd_functional import (  # noqa: E402
    Hessian, Jacobian, hessian, jacobian,
)
