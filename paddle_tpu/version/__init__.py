"""``paddle.version`` (ref: generated ``python/paddle/version/__init__.py``)."""
# single source of truth: the package __version__ (bound before this
# optional submodule imports)
from paddle_tpu import __version__ as full_version

_parts = (full_version.split(".") + ["0", "0"])[:3]
major, minor, patch = _parts
rc = "0"
istaged = True
commit = "unknown"
with_mkl = "OFF"
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"

__all__ = ["full_version", "major", "minor", "patch", "rc", "show",
           "cuda", "cudnn", "xpu"]


def show():
    """Print the installed version breakdown (ref ``version.show()``)."""
    if istaged:
        print("full_version:", full_version)
        print("major:", major)
        print("minor:", minor)
        print("patch:", patch)
        print("rc:", rc)
    else:
        print("commit:", commit)
    print("cuda:", cuda_version)
    print("cudnn:", cudnn_version)
    print("xpu:", xpu_version)


def cuda():
    """No CUDA on this stack (TPU/XLA); parity returns 'False'."""
    return cuda_version


def cudnn():
    return cudnn_version


def xpu():
    return xpu_version
