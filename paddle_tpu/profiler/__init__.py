"""``paddle.profiler`` — profiling API.

TPU-native re-design of the reference profiler stack
(``python/paddle/profiler/profiler.py:349`` Profiler, ``make_scheduler
:117``, ``export_chrome_tracing :215``; C++ host tracer
``paddle/fluid/platform/profiler/host_tracer.cc``, CUPTI tracer
``cuda_tracer.cc``):

 - host spans come from the native core tracer (``paddle_tpu.core``
   RecordEvent → libptcore), exported as chrome://tracing JSON — the
   ``chrometracing_logger.cc`` equivalent;
 - device timing comes from the XLA/jax profiler (xplane protobufs under
   the logdir, viewable in TensorBoard/XProf) — the CUPTI equivalent;
 - the state machine (CLOSED/READY/RECORD[_AND_RETURN]) and scheduler
   semantics match the reference so training-loop integrations carry over.
"""
from __future__ import annotations

import enum
import os
from typing import Callable, Iterable, Optional

from .. import core as _core
from ..core import RecordEvent  # noqa: F401  (public, same name as ref)
from . import utils  # noqa: F401

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "make_scheduler",
    "export_chrome_tracing", "RecordEvent", "load_profiler_result",
    "SortedKeys", "SummaryView",
]


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1      # accepted for API parity; maps to the XLA device trace
    TPU = 2
    CUSTOM_DEVICE = 3


class SortedKeys(enum.Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    Calls = 4


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    """Ref ``profiler.py:117``: per-step state schedule
    [skip_first][closed][ready][record] x repeat."""
    period = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_schedule(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def _dump_chrome(path: str) -> None:
    """Single-sink chrome export: when the observability tracer is
    enabled, its span window IS the trace (every RecordEvent is
    forwarded there, plus step-phase spans, stamped with rank/run_id);
    otherwise fall back to the raw core tracer dump."""
    try:
        from ..observability.trace import get_tracer
        tr = get_tracer()
    except Exception:
        tr = None
    if tr is not None and tr.enabled:
        if tr.export_chrome(path) is not None:
            return
    _core.tracer_dump(path)


def export_chrome_tracing(dir_name: str, worker_name: str | None = None):
    """Ref ``profiler.py:215``: returns an on_trace_ready callback that dumps
    chrome://tracing JSON into ``dir_name``."""

    def handle(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_step{prof.step_num}.json")
        _dump_chrome(path)
        prof._exported_paths.append(path)

    return handle


def export_protobuf(dir_name: str, worker_name: str | None = None):
    """Parity alias — the xplane protobufs that jax writes under the logdir
    are the protobuf export; host spans still dump as chrome JSON."""
    return export_chrome_tracing(dir_name, worker_name)


class _EventStat:
    __slots__ = ("name", "calls", "total_ns", "max_ns", "min_ns")

    def __init__(self, name):
        self.name = name
        self.calls = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = 1 << 62

    def add(self, dur):
        self.calls += 1
        self.total_ns += dur
        self.max_ns = max(self.max_ns, dur)
        self.min_ns = min(self.min_ns, dur)


class SummaryView:
    """Aggregated host-event table (the reference's summary printer)."""

    def __init__(self, events):
        stats: dict[str, _EventStat] = {}
        for (name, _start, dur, _tid) in events:
            stats.setdefault(name, _EventStat(name)).add(dur)
        self.rows = sorted(stats.values(), key=lambda s: -s.total_ns)

    def table(self, sorted_by: SortedKeys = SortedKeys.CPUTotal) -> str:
        key = {
            SortedKeys.CPUTotal: lambda s: -s.total_ns,
            SortedKeys.CPUAvg: lambda s: -(s.total_ns / max(s.calls, 1)),
            SortedKeys.CPUMax: lambda s: -s.max_ns,
            SortedKeys.CPUMin: lambda s: s.min_ns,
            SortedKeys.Calls: lambda s: -s.calls,
        }[sorted_by]
        rows = sorted(self.rows, key=key)
        out = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
               f"{'Max(ms)':>10}{'Min(ms)':>10}"]
        out.append("-" * 90)
        for s in rows:
            out.append(
                f"{s.name[:39]:<40}{s.calls:>8}"
                f"{s.total_ns / 1e6:>12.3f}"
                f"{s.total_ns / max(s.calls, 1) / 1e6:>10.3f}"
                f"{s.max_ns / 1e6:>10.3f}"
                f"{(0 if s.calls == 0 else s.min_ns) / 1e6:>10.3f}")
        return "\n".join(out)

    def __str__(self):
        return self.table()


class Profiler:
    """``paddle.profiler.Profiler`` equivalent.

    ``targets`` including a device target also starts the jax/XLA device
    trace (xplane under ``profile_path``); host spans always record through
    the native tracer.
    """

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False,
                 with_flops=False, profile_path="./profiler_log"):
        self.targets = list(targets) if targets is not None else [
            ProfilerTarget.CPU]
        if callable(scheduler):
            self.scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            start, end = scheduler
            self.scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        elif scheduler is None:
            self.scheduler = _default_schedule
        else:
            raise TypeError("scheduler must be callable, (start, end) or "
                            "None")
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.profile_path = profile_path
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._device_tracing = False
        self._exported_paths: list[str] = []

    # -- device (XLA) trace ----------------------------------------------
    def _device_targets(self):
        return any(t in (ProfilerTarget.GPU, ProfilerTarget.TPU,
                         ProfilerTarget.CUSTOM_DEVICE)
                   for t in self.targets)

    def _start_device_trace(self):
        if self._device_tracing or self.timer_only:
            return
        if self._device_targets():
            try:
                import jax
                jax.profiler.start_trace(self.profile_path)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False

    def _stop_device_trace(self):
        if self._device_tracing:
            import jax
            try:
                jax.profiler.stop_trace()
            finally:
                self._device_tracing = False

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.current_state = self.scheduler(self.step_num)
        if self.current_state != ProfilerState.CLOSED:
            self._enter_recording()

    def _enter_recording(self):
        if not self.timer_only:
            _core.tracer_enable()
        self._start_device_trace()

    def _exit_recording(self):
        _core.tracer_disable()
        self._stop_device_trace()

    def step(self, num_samples=None):
        prev = self.current_state
        self.step_num += 1
        self.current_state = self.scheduler(self.step_num)
        if prev == ProfilerState.RECORD_AND_RETURN or (
                prev in (ProfilerState.RECORD,)
                and self.current_state == ProfilerState.CLOSED):
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
        if prev == ProfilerState.CLOSED and \
                self.current_state != ProfilerState.CLOSED:
            self._enter_recording()
        elif prev != ProfilerState.CLOSED and \
                self.current_state == ProfilerState.CLOSED:
            self._exit_recording()

    def stop(self):
        if self.current_state != ProfilerState.CLOSED:
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
            self._exit_recording()
        self.current_state = ProfilerState.CLOSED

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results -----------------------------------------------------------
    def summary(self, sorted_by: SortedKeys = SortedKeys.CPUTotal,
                op_detail=True, thread_sep=False, time_unit="ms"):
        view = SummaryView(_core.tracer_events())
        return view.table(sorted_by)

    def export(self, path: str, format: str = "json"):
        _dump_chrome(path)


def load_profiler_result(filename: str):
    """Load a chrome-tracing JSON exported by this profiler."""
    import json
    with open(filename) as f:
        return json.load(f)
