"""Profiler utilities (ref: ``python/paddle/profiler/utils.py``)."""
from __future__ import annotations

import functools

from ..core import RecordEvent

__all__ = ["wrap_optimizers", "benchmark", "record_function"]


def record_function(name):
    """Decorator: wrap a function in a host RecordEvent span."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with RecordEvent(name):
                return fn(*a, **k)
        return wrapper
    return deco


def wrap_optimizers():
    """Instrument Optimizer.step with RecordEvent spans (the reference
    patches optimizer classes the same way)."""
    from .. import optimizer as opt_mod
    base = opt_mod.Optimizer
    if getattr(base, "_profiler_wrapped", False):
        return
    orig = base.step

    @functools.wraps(orig)
    def step(self, *a, **k):
        with RecordEvent(f"Optimizer.step#{type(self).__name__}"):
            return orig(self, *a, **k)

    base.step = step
    base._profiler_wrapped = True


class benchmark:
    """Minimal ips/latency helper (ref ``utils.py`` benchmark context)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._times = []

    def begin(self):
        import time
        self._t0 = time.perf_counter()

    def end(self, num_samples=1):
        import time
        dt = time.perf_counter() - self._t0
        self._times.append((dt, num_samples))

    def report(self):
        if not self._times:
            return {}
        total = sum(t for t, _ in self._times)
        samples = sum(n for _, n in self._times)
        return {"steps": len(self._times), "total_s": total,
                "avg_latency_s": total / len(self._times),
                "ips": samples / total if total else 0.0}
