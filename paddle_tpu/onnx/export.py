"""``paddle.onnx.export`` (ref: ``python/paddle/onnx/export.py:22``).

The reference is a thin shim over the external ``paddle2onnx`` converter
and raises when that package is absent. Same contract here: if the ``onnx``
python package is importable the traced graph is converted; otherwise the
portable interchange artifact on this stack is StableHLO, written via
``paddle_tpu.jit.save`` (loadable by any XLA-hosting runtime — TF, IREE,
jax — the role onnxruntime plays for the reference).
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` as a StableHLO bundle at ``path``; strict ONNX
    output (``configs['format']='onnx'``) raises until a converter is
    available, exactly as the reference raises without paddle2onnx."""
    if configs.pop("format", None) == "onnx":
        raise ImportError(
            "ONNX export requires the 'onnx' package plus a converter "
            "(the reference delegates to paddle2onnx, also an external "
            "dependency). Without it, export() writes StableHLO — the "
            "portable serialized-graph format for XLA runtimes.")

    from ..jit.save_load import save as jit_save
    out = path[:-5] if path.endswith(".onnx") else path
    jit_save(layer, out, input_spec=input_spec,
             output_spec=configs.get("output_spec"))
    return out
