"""``paddle.inference`` — the deployment predictor API.

TPU-native re-design of the reference inference stack
(``paddle/fluid/inference/``, 87K LoC: AnalysisPredictor
``analysis_predictor.cc``, IR passes, TensorRT/ONNXRT engines, zero-copy
tensors ``paddle_infer::Tensor``):

 - the IR-optimization + engine-selection pipeline collapses into XLA AOT:
   the artifact is serialized StableHLO (from ``jit.save`` or
   ``static.save_inference_model``) compiled once per shape at load;
 - ``Config``/``create_predictor``/``Predictor``/input-output handles keep
   the reference's API so serving code ports over;
 - "zero copy" is the default: handles wrap device arrays, and host→device
   transfer happens once per ``copy_from_cpu``.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.export  # noqa: F401  (binds the submodule attr; not re-exported on older jax)
import jax.numpy as jnp

from ..tensor import Tensor as _PTensor

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "PredictorPool", "PlaceType", "DataType", "PrecisionType",
           "get_version", "get_num_bytes_of_data_type",
           "convert_to_mixed_precision", "get_trt_compile_version",
           "get_trt_runtime_version", "XpuConfig", "_get_phi_kernel_name"]


class PrecisionType:
    """ref ``paddle/fluid/inference/api/paddle_analysis_config.h``
    Precision enum; bf16 is the TPU-native half type."""
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class XpuConfig:
    """Accepted-for-parity XPU tuning knobs (no XPU in this build)."""

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, k, v)


def get_version():
    from .. import __version__
    return f"version : {__version__}"


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT in a TPU build


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype):
    import numpy as np
    if dtype == DataType.BFLOAT16:
        return 2
    return int(np.dtype(dtype).itemsize)  # DataType members ARE np names


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    XPU = "xpu"
    CUSTOM = "custom"


class DataType:
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT8 = "int8"
    INT32 = "int32"
    INT64 = "int64"


class Config:
    """``paddle_infer.Config`` analog. GPU/TRT/MKLDNN toggles are accepted
    and inert (XLA owns optimization on TPU)."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle 2.x: Config(model_dir) or Config(prog, params) — here the
        # artifact is a path prefix (jit.save / save_inference_model);
        # a reference-style full .pdmodel file path is accepted too
        if isinstance(prog_file, str) and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_prefix = prog_file
        self._device = PlaceType.TPU
        self._memory_optim = True
        self._glog_info = False

    def set_prog_file(self, path):
        self.model_prefix = path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = PlaceType.GPU  # accepted; runs on the jax backend

    def enable_xpu(self, *a, **k):
        self._device = PlaceType.XPU

    def disable_gpu(self):
        self._device = PlaceType.CPU

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes

    def enable_tensorrt_engine(self, *a, **k):
        pass  # no TRT on TPU; XLA compiles the whole graph

    def enable_mkldnn(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def summary(self):
        return (f"Config(model={self.model_prefix}, device={self._device}, "
                f"memory_optim={self._memory_optim})")


class Tensor:
    """IO handle (ref ``paddle_infer::Tensor``): named slot with
    copy_from_cpu / copy_to_cpu; device array underneath."""

    def __init__(self, name, spec=None):
        self.name = name
        self._spec = spec
        self._value = None

    def reshape(self, shape):
        pass  # shape comes from the copied array

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(arr)

    def share_external_data(self, arr):
        if isinstance(arr, _PTensor):
            arr = arr._data
        self._value = arr  # no copy

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else \
            (list(self._spec["shape"]) if self._spec else [])

    def type(self):
        return str(self._value.dtype) if self._value is not None else \
            (self._spec or {}).get("dtype", "float32")


class _HostTensor(Tensor):
    """Input handle that stays on the host: the serving engine's
    request path is numpy-only (a ``jnp.asarray`` here would book a
    tiny convert compile and trip the serve zero-compile sentinel)."""

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def share_external_data(self, arr):
        if isinstance(arr, _PTensor):
            arr = arr._data
        self._value = np.asarray(arr)


class Predictor:
    """Loads a StableHLO artifact and serves it (AnalysisPredictor
    analog).  A served-model directory (``serve_config.json`` written
    by ``serving.save_served_model``) routes through the AOT serving
    engine instead."""

    def __init__(self, config: Config, _share_from: "Predictor" = None):
        prefix = config.model_prefix
        if prefix is None:
            raise ValueError("Config needs a model path prefix")
        self.config = config
        if _share_from is not None:
            # share the deserialized program + weights (PredictorPool):
            # only the IO handles are per-predictor
            self._call = _share_from._call
            self._engine = getattr(_share_from, "_engine", None)
            self._in_names = list(_share_from._in_names)
            self._in_specs = list(_share_from._in_specs)
            self._out_names = (list(_share_from._out_names)
                               if _share_from._out_names else None)
            tcls = _HostTensor if self._engine is not None else Tensor
            self._inputs = {n: tcls(n, s) for n, s in
                            zip(self._in_names, self._in_specs)}
            self._outputs = None
            return
        self._load(prefix)

    def _load(self, prefix):
        self._engine = None
        from ..serving.engine import is_served_model_dir
        if is_served_model_dir(prefix):  # serving-engine model dir
            self._load_served(prefix)
            return
        if os.path.exists(prefix + ".stablehlo"):  # jit.save artifact
            from ..jit.save_load import load as jit_load
            layer = jit_load(prefix)
            self._call = lambda *xs: _ensure_tuple(
                layer._exported.call(layer._param_arrays,
                                     layer._buffer_arrays, *xs))
            specs = layer._manifest.get("input_specs", [])
            self._in_names = [f"x{i}" for i in range(len(specs))]
            self._in_specs = specs
            self._out_names = None
        elif os.path.exists(prefix + ".pdmodel"):  # static artifact
            from ..static.io import load_inference_model
            prog, feeds, fetches = load_inference_model(prefix)
            self._call = lambda *xs: _ensure_tuple(prog(*xs))
            self._in_names = list(feeds)
            self._in_specs = [None] * len(feeds)
            self._out_names = list(fetches)
        else:
            raise FileNotFoundError(
                f"no inference artifact at '{prefix}' (.stablehlo from "
                "jit.save or .pdmodel from save_inference_model)")
        self._inputs = {n: Tensor(n, s)
                        for n, s in zip(self._in_names, self._in_specs)}
        self._outputs = None

    def _load_served(self, path):
        """Route a served-model dir (``serve_config.json`` + weights)
        through the AOT serving engine: same Predictor surface, but
        run() is a full generate loop over the zero-compile serve
        graphs instead of a single forward."""
        from ..serving import load_engine
        engine = load_engine(path)
        self._engine = engine

        def _generate(tokens):
            prompt = [int(t) for t in np.asarray(tokens).reshape(-1)]
            out = engine.generate([prompt])[0]
            return (np.asarray(out, np.int32),)

        self._call = _generate
        self._in_names = ["tokens"]
        self._in_specs = [{"shape": [-1], "dtype": "int32"}]
        self._out_names = ["generated_ids"]
        self._inputs = {n: _HostTensor(n, s)
                        for n, s in zip(self._in_names, self._in_specs)}
        self._outputs = None

    # -- reference API ------------------------------------------------------
    def get_input_names(self):
        return list(self._in_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        """Either Predictor.run() after copy_from_cpu on handles, or the
        2.x convenience run([arrays...]) returning arrays."""
        if inputs is not None:
            if len(inputs) != len(self._in_names):
                raise ValueError(
                    f"model takes {len(self._in_names)} inputs "
                    f"({self._in_names}), got {len(inputs)}")
            for n, a in zip(self._in_names, inputs):
                self._inputs[n].copy_from_cpu(
                    a._data if isinstance(a, _PTensor) else a)
        args = [self._inputs[n]._value for n in self._in_names]
        if any(a is None for a in args):
            missing = [n for n in self._in_names
                       if self._inputs[n]._value is None]
            raise ValueError(f"inputs not set: {missing}")
        outs = self._call(*args)
        names = self._out_names or [f"out{i}" for i in range(len(outs))]
        self._outputs = {}
        for n, o in zip(names, outs):
            h = Tensor(n)
            h._value = o
            self._outputs[n] = h
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def get_output_names(self):
        if self._outputs is None:
            return list(self._out_names or [])
        return list(self._outputs)

    def get_output_handle(self, name):
        if self._outputs is None:
            raise RuntimeError("run() first")
        return self._outputs[name]

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def _ensure_tuple(x):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    """N independent predictors over one artifact (ref
    ``paddle_infer::services::PredictorPool``). On TPU they share the
    compiled executable (XLA caches by computation)."""

    def __init__(self, config: Config, size: int = 1):
        first = Predictor(config)
        self._preds = [first] + [Predictor(config, _share_from=first)
                                 for _ in range(size - 1)]

    def retrive(self, idx):  # reference spells it "retrive"
        return self._preds[idx]

    retrieve = retrive


def _get_phi_kernel_name(op_name):
    """ref ``inference/__init__.py``: fluid-op -> phi-kernel name map.
    This build has no phi registry — op names ARE the jax-function
    names, so the mapping is the identity."""
    return op_name


def convert_to_mixed_precision(src_model, src_params, dst_model,
                               dst_params, mixed_precision="bfloat16",
                               backend=None, black_list=None, **kwargs):
    """Convert a saved inference artifact to low-precision WEIGHTS (ref
    ``inference/__init__.py convert_to_mixed_precision`` over the
    mixed-precision pass).

    The exported StableHLO blob pins its compute dtypes, so this
    implements the storage half of the pass: float32 params (minus
    ``black_list`` names) are stored as ``mixed_precision`` and upcast
    inside a re-exported wrapper program — halving the artifact +
    resident weight bytes, which is the part that matters on HBM-bound
    TPU serving."""
    import pickle

    import jax

    table = {"half": jnp.float16, "float16": jnp.float16,
             "fp16": jnp.float16, "bfloat16": jnp.bfloat16,
             "bf16": jnp.bfloat16, PrecisionType.Half: jnp.float16,
             PrecisionType.Bfloat16: jnp.bfloat16}
    key = mixed_precision.lower() if isinstance(mixed_precision, str) \
        else mixed_precision
    prec = table.get(key)
    if prec is None:
        raise ValueError(
            f"mixed_precision must be float16/bfloat16 (or the matching "
            f"PrecisionType); got {mixed_precision!r}")
    black = set(black_list or ())

    with open(src_model, "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(src_params, "rb") as f:
        payload = pickle.load(f)
    params = payload["params"]
    orig_dtypes = {k: np.asarray(v).dtype for k, v in params.items()}
    cast_params = {
        k: (np.asarray(v).astype(prec)
            if np.asarray(v).dtype == np.float32 and k not in black
            else np.asarray(v))
        for k, v in params.items()}

    def wrapped(p, *feeds):
        restored = {k: jnp.asarray(v).astype(orig_dtypes[k])
                    for k, v in p.items()}
        return exported.call(restored, *feeds)

    param_specs = {k: jax.ShapeDtypeStruct(np.asarray(v).shape, v.dtype)
                   for k, v in cast_params.items()}
    # in_avals flattens (params_dict, *feeds): dict leaves first
    feed_specs = list(exported.in_avals[len(cast_params):])
    new_exported = jax.export.export(jax.jit(wrapped))(param_specs,
                                                       *feed_specs)
    for dst in (dst_model, dst_params):
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
    with open(dst_model, "wb") as f:
        f.write(new_exported.serialize())
    with open(dst_params, "wb") as f:
        pickle.dump({**payload, "params": cast_params}, f)
