"""Dynamic loss scaling (ref: ``python/paddle/amp/grad_scaler.py:576``).

On TPU with bf16 AMP, scaling is mathematically unnecessary (bf16 has fp32's
exponent); the scaler then degenerates to a pass-through that still tracks
found_inf for parity. With float16 it performs real dynamic scaling.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["GradScaler", "AmpScaler", "OptimizerState"]


class OptimizerState:
    INIT, UNSCALED, STEPPED = 0, 1, 2


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._found_inf_param = None
        self._opt_states = {}

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def get_scale_ratio(self):
        return self._scale

    # paddle API names
    def is_enabled(self):
        return self._enable

    def scale(self, var):
        from ..ops.math import multiply
        if not self._enable:
            return var
        return multiply(var, self._scale)

    def _check_grads(self, optimizer):
        found = False
        self._found_inf_param = None
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data
            # eager AMP legitimately syncs here: the skip decision IS
            # the host branch. Captured steps route through the
            # in-graph numerics monitor instead.
            # tpu-lint: disable=TPU017
            if bool(jnp.any(~jnp.isfinite(g.astype(jnp.float32)))):
                found = True
                self._found_inf_param = getattr(p, "name", None)
                break
        self._found_inf = found
        return found

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if self._opt_states.get(id(optimizer)) == OptimizerState.UNSCALED:
            return
        inv = 1.0 / self._scale
        for p in optimizer._parameter_list:
            if p.grad is not None:
                p.grad._data = p.grad._data * np.asarray(
                    inv, dtype=np.float32).astype(p.grad._data.dtype)
        self._opt_states[id(optimizer)] = OptimizerState.UNSCALED

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._check_grads(optimizer):
            optimizer.step()
        else:
            # a skipped step is a classified anomaly, not silence: AMP
            # runs surface their skip rate through the same counter as
            # every other numerics trip (never halts — the skip IS the
            # scaler's recovery mechanism)
            from ..observability.numerics import get_monitor
            get_monitor().record_anomaly(
                "scaler_skip", tensor=self._found_inf_param,
                detail="loss_scale=%g" % self._scale, halt_ok=False)
        self._opt_states[id(optimizer)] = OptimizerState.STEPPED

    def update(self):
        if not (self._enable and self._use_dynamic):
            self._opt_states.clear()
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._opt_states.clear()

    def minimize(self, optimizer, loss, **kwargs):
        self.step(optimizer)
        self.update()

    # state io
    def state_dict(self):
        # fields may be lazy device scalars after a compiled train step
        return {
            "scale": float(self._scale), "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": int(self._good_steps),
            "bad_steps": int(self._bad_steps),
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)

    def get_loss_scaling(self):
        return float(self._scale)

    def set_init_loss_scaling(self, v):
        self._scale = float(v)


class GradScaler(AmpScaler):
    """paddle.amp.GradScaler."""
    pass
