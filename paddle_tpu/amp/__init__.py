"""Automatic mixed precision.

TPU-native re-design of the reference AMP stack:
 - ``auto_cast`` context (``python/paddle/amp/auto_cast.py:646``,
   amp_guard ``:271``) with O1 white/black lists (``amp_lists.py``)
 - ``GradScaler`` dynamic loss scaling (``grad_scaler.py:576``)

TPU differences by design:
 - default AMP dtype is **bfloat16**, which shares float32's exponent range,
   so loss scaling is unnecessary — GradScaler is provided for API parity
   and for float16 mode, and is a near-no-op for bf16.
 - O2 ("pure" mode) maps to casting parameters once (`decorate`), the
   standard TPU recipe (params in bf16, optimizer state fp32).
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate", "GradScaler",
           "AmpScaler", "is_bfloat16_supported", "is_float16_supported",
           "white_list", "black_list", "debugging"]

# O1 op lists — mirrors python/paddle/amp/amp_lists.py
WHITE_LIST = {
    "matmul", "bmm", "einsum", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "linear", "mm", "mv",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "mean", "sum", "softmax",
    "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "layer_norm", "norm", "cumsum", "logsumexp", "erfinv", "pow",
    "square", "reciprocal", "rsqrt",
}


class _AmpState:
    __slots__ = ("enable", "dtype", "level", "white", "black")

    def __init__(self, enable, dtype, level, white, black):
        self.enable = enable
        self.dtype = dtype
        self.level = level
        self.white = white
        self.black = black


_tls = threading.local()


def _current_state():
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _cast_for_op(state, op_name, tensors):
    from ..tensor import Tensor
    if state.level == "O0" or op_name in state.black:
        return tensors
    if state.level == "O2" or op_name in state.white:
        target = state.dtype
        out = []
        for t in tensors:
            if t is None or not isinstance(t, Tensor):
                out.append(t)
                continue
            d = np.dtype(t._data.dtype)
            if (np.issubdtype(d, np.floating) or d == jnp.bfloat16) \
                    and d != target:
                out.append(t.astype(target))
            else:
                out.append(t)
        return tuple(out)
    return tensors


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """``paddle.amp.auto_cast`` equivalent."""
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    white = set(WHITE_LIST) | set(custom_white_list or ())
    black = (set(BLACK_LIST) | set(custom_black_list or ())) - set(
        custom_white_list or ())
    state = _AmpState(enable, to_jax_dtype(dtype), level, white, black)
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(state)
    try:
        yield
    finally:
        stack.pop()


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """``paddle.amp.decorate``: cast model params for pure-bf16/fp16 (O2).

    Master weights: optimizers keep fp32 copies automatically when
    ``multi_precision`` is on (the default for Adam/Momentum here), mirroring
    the reference's master-weight machinery.
    """
    if level not in ("O1", "O2"):
        raise ValueError("decorate only supports O1/O2")
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        dt = to_jax_dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                d = np.dtype(p._data.dtype)
                if np.issubdtype(d, np.floating) or d == jnp.bfloat16:
                    p._data = p._data.astype(dt)
    if optimizers is None:
        return models
    return models, optimizers


amp_decorate = decorate


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


def white_list():
    return {"bfloat16": {"O1": sorted(WHITE_LIST)},
            "float16": {"O1": sorted(WHITE_LIST)}}


def black_list():
    return {"bfloat16": {"O1": sorted(BLACK_LIST)},
            "float16": {"O1": sorted(BLACK_LIST)}}


# debugging helpers (ref: python/paddle/amp/debugging.py)
def check_numerics(x, op_name="", debug_mode=None):
    """Pass-through finiteness guard. The counting/reporting API is
    :func:`paddle_tpu.amp.debugging.check_numerics` (ref
    ``debugging.py:265``); this wrapper shares it rather than
    re-implementing the scan, and returns ``x`` for chaining."""
    import jax
    from ..tensor import Tensor
    if isinstance(x, Tensor) and not isinstance(x._data, jax.core.Tracer):
        from .debugging import DebugMode
        from .debugging import check_numerics as _cn
        _cn(x, op_name or "op", "x",
            debug_mode or DebugMode.CHECK_NAN_INF_AND_ABORT)
    return x


from . import debugging  # noqa: E402,F401  (amp.debugging.* tooling)
