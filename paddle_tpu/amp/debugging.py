"""AMP debugging / accuracy tooling.

ref: ``python/paddle/amp/debugging.py`` (``collect_operator_stats``,
``TensorCheckerConfig``/``enable_tensor_checker``, ``compare_accuracy``)
and ``python/paddle/amp/accuracy_compare.py``. On a bf16-first TPU stack
this is how users localize loss blow-ups: count which ops ran in which
dtype, find the first op producing NaN/Inf, and diff fp32-vs-low-precision
activations per layer. All three ride the single op funnel
(``autograd.add_op_observer``) instead of the reference's codegen'd
per-op hooks.
"""
from __future__ import annotations

import contextlib
import enum

import numpy as np
import jax
import jax.numpy as jnp

from .. import autograd as _autograd
from ..framework import flags as _flags
from ..tensor import Tensor

__all__ = [
    "DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
    "disable_tensor_checker", "enable_operator_stats_collection",
    "disable_operator_stats_collection", "collect_operator_stats",
    "compare_accuracy", "check_numerics",
]


# -- operator stats ---------------------------------------------------------

_stats = None
_stats_observer = None


def _observe_stats(name, inputs, outputs):
    for t in outputs:
        d = getattr(t, "_data", None)
        if d is None:
            continue
        dt = str(np.dtype(d.dtype)) if d.dtype != jnp.bfloat16 \
            else "bfloat16"
        key = name or "unknown"
        _stats.setdefault(key, {}).setdefault(dt, 0)
        _stats[key][dt] += 1


def enable_operator_stats_collection():
    """Start counting (op, output dtype) occurrences
    (ref ``debugging.py enable_operator_stats_collection``)."""
    global _stats, _stats_observer
    _stats = {}
    _stats_observer = _observe_stats
    _autograd.add_op_observer(_stats_observer)


def disable_operator_stats_collection():
    """Stop collection and print the four-bucket table like the
    reference (fp32 / fp16 / bf16 / other calls per op)."""
    global _stats_observer
    if _stats_observer is not None:
        _autograd.remove_op_observer(_stats_observer)
        _stats_observer = None
    _print_operator_stats(_stats or {})
    return _stats


def _print_operator_stats(stats):
    print("<{:-^120}>".format(" op list "))
    row = "<{:-^40}" + "|{:-^17}" * 4 + ">"
    print(row.format(" Op Name ", " FP16 Calls ", " BF16 Calls ",
                     " FP32 Calls ", " Other Calls "))
    for op in sorted(stats):
        d = stats[op]
        other = sum(v for k, v in d.items()
                    if k not in ("float16", "bfloat16", "float32"))
        print("<{:-^40}|{:-^17}|{:-^17}|{:-^17}|{:-^17}>".format(
            op, d.get("float16", 0), d.get("bfloat16", 0),
            d.get("float32", 0), other))


@contextlib.contextmanager
def collect_operator_stats():
    """``with collect_operator_stats(): ...`` (ref ``debugging.py:464``)."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


# -- tensor checker (nan/inf localization) ----------------------------------

class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    """ref ``debugging.py TensorCheckerConfig``: which ops to watch and
    what to do on a non-finite output."""

    def __init__(self, enable=True,
                 debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        self.debug_step = debug_step


_checker_cfg = None
_checker_observer = None
_checker_findings: list = []


def _observe_checker(name, inputs, outputs):
    cfg = _checker_cfg
    if cfg is None:
        return
    key = name or "unknown"
    if cfg.checked_op_list and key not in cfg.checked_op_list:
        return
    if key in cfg.skipped_op_list:
        return
    for t in outputs:
        d = getattr(t, "_data", None)
        if d is None or isinstance(d, jax.core.Tracer):
            continue
        if not (np.issubdtype(np.dtype(d.dtype), np.floating)
                or d.dtype == jnp.bfloat16):
            continue
        # the debugging checker's whole contract is an eager
        # host-side audit of materialized values
        # tpu-lint: disable=TPU017
        bad = int(jnp.size(d) - jnp.isfinite(
            d.astype(jnp.float32)).sum())
        if bad:
            finding = {"op": key, "num_nan_inf": bad,
                       "shape": tuple(d.shape), "dtype": str(d.dtype)}
            _checker_findings.append(finding)
            if cfg.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
                raise FloatingPointError(
                    f"TensorChecker: {bad} NaN/Inf values in output of "
                    f"op '{key}' shape={tuple(d.shape)}")
            print(f"[TensorChecker] op={key} nan/inf={bad} "
                  f"shape={tuple(d.shape)} dtype={d.dtype}")


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """ref ``debugging.py enable_tensor_checker``: every funnel op's
    outputs are scanned; the FIRST offending op is named (the
    localization the reference gets from per-kernel nan-inf utils)."""
    global _checker_cfg, _checker_observer
    if not checker_config.enable:
        return
    _checker_cfg = checker_config
    _checker_findings.clear()
    _checker_observer = _observe_checker
    _autograd.add_op_observer(_checker_observer)


def disable_tensor_checker():
    """Returns the findings accumulated while enabled."""
    global _checker_cfg, _checker_observer
    if _checker_observer is not None:
        _autograd.remove_op_observer(_checker_observer)
        _checker_observer = None
    _checker_cfg = None
    return list(_checker_findings)


# -- fp32 vs low-precision accuracy compare ---------------------------------

def compare_accuracy(layer, inputs, dtype="bfloat16", atol=1e-2, rtol=1e-2,
                     print_report=True):
    """Per-sublayer fp32-vs-``dtype`` forward activation diff
    (ref ``amp/accuracy_compare.py`` — the reference diffs two dumped
    run logs; here both runs happen in-process via forward hooks).

    Returns a list of rows ``{"layer", "type", "max_abs_diff",
    "mean_abs_diff", "exceeds"}`` ordered by execution; the first
    ``exceeds`` row is where low-precision diverges past
    ``atol + rtol*|fp32|``.
    """
    from . import auto_cast

    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    inputs = [x if isinstance(x, Tensor) else Tensor(x) for x in inputs]

    def run(low_precision):
        captured = []
        hooks = []

        def make_hook(name, sub):
            def hook(lyr, ins, out):
                o = out[0] if isinstance(out, (list, tuple)) else out
                if isinstance(o, Tensor):
                    captured.append(
                        (name, type(lyr).__name__,
                         np.asarray(o._data.astype(jnp.float32))))
            return hook

        for name, sub in layer.named_sublayers(include_self=False):
            hooks.append(sub.register_forward_post_hook(
                make_hook(name, sub)))
        was_training = layer.training
        try:
            layer.eval()
            if low_precision:
                with auto_cast(enable=True, dtype=dtype, level="O1"):
                    layer(*inputs)
            else:
                layer(*inputs)
        finally:
            if was_training:
                layer.train()
            for h in hooks:
                h.remove()
        return captured

    ref = run(False)
    low = run(True)
    rows = []
    for (name, ltype, a), (_, _, b) in zip(ref, low):
        if a.shape != b.shape:
            continue
        diff = np.abs(a - b)
        thresh = atol + rtol * np.abs(a)
        rows.append({
            "layer": name, "type": ltype,
            "max_abs_diff": float(diff.max()) if diff.size else 0.0,
            "mean_abs_diff": float(diff.mean()) if diff.size else 0.0,
            "exceeds": bool((diff > thresh).any()),
        })
    if print_report:
        print(f"{'layer':<40}{'type':<24}{'max_abs':>12}{'mean_abs':>12}"
              f"{'exceeds':>9}")
        for r in rows:
            print(f"{r['layer']:<40}{r['type']:<24}"
                  f"{r['max_abs_diff']:>12.3e}{r['mean_abs_diff']:>12.3e}"
                  f"{str(r['exceeds']):>9}")
    return rows


def check_numerics(tensor, op_type, var_name,
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Count NaN/Inf/zero and report max/min/mean of ``tensor`` (ref
    ``amp/debugging.py:265``). Returns (stats[3] int64, values[3]
    float32); under CHECK_NAN_INF_AND_ABORT a non-finite tensor raises.
    """
    import jax.numpy as jnp
    from ..tensor import Tensor
    d = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    f = d.astype(jnp.float32)
    n_nan = jnp.isnan(f).sum()
    n_inf = jnp.isinf(f).sum()
    n_zero = (f == 0).sum()
    stats = jnp.stack([n_nan, n_inf, n_zero]).astype(jnp.int64)
    finite = jnp.where(jnp.isfinite(f), f, jnp.nan)
    values = jnp.stack([jnp.nanmax(finite), jnp.nanmin(finite),
                        jnp.nanmean(finite)])
    bad = int(n_nan) + int(n_inf)
    if bad and debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise FloatingPointError(
            f"[check_numerics] op={op_type} var={var_name}: "
            f"{int(n_nan)} NaN, {int(n_inf)} Inf")
    if bad and debug_mode == DebugMode.CHECK_NAN_INF:
        print(f"[check_numerics] op={op_type} var={var_name}: "
              f"{int(n_nan)} NaN, {int(n_inf)} Inf")
    return Tensor(stats), Tensor(values)
