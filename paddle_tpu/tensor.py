"""The Tensor type.

TPU-native re-design of the reference's tensor stack:
 - ``phi::DenseTensor`` (``paddle/phi/core/dense_tensor.h:43``) +
   ``paddle::Tensor`` (``paddle/phi/api/include/tensor.h:82``) +
   the pybind eager Tensor (``paddle/fluid/pybind/eager.cc``), collapsed into
   one Python class wrapping a ``jax.Array``.

Memory, placement and layout are owned by XLA/PJRT (no allocator facade, no
LoD, no layout transform pass — ``paddle/fluid/memory/allocation`` has no
equivalent here by design). The autograd surface (``stop_gradient``, ``.grad``,
``backward()``) matches the reference's dygraph tensor so training scripts
carry over.

Tensor is registered as a jax pytree, so Tensors can flow directly through
``jax.jit`` / ``shard_map`` / optimizers as containers of their arrays.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from .framework import dtype as _dtype_mod  # noqa: F401  (module via package)
from .framework.dtype import (DType, to_jax_dtype, default_jax_dtype,
                              dtype as _as_dtype, _BY_NAME)
from .framework.device import (CPUPlace, TPUPlace, Place, get_jax_device)

__all__ = ["Tensor", "Parameter", "to_tensor", "is_tensor"]

_tensor_count = 0


def _next_name(prefix="generated_tensor"):
    global _tensor_count
    _tensor_count += 1
    return f"{prefix}_{_tensor_count}"


class Tensor:
    __slots__ = ("_data", "stop_gradient", "_grad", "_node", "_out_idx",
                 "name", "persistable", "_grad_hooks", "__weakref__",
                 "trainable", "_spec")

    __array_priority__ = 100  # win over numpy in mixed dunders

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            data = np.asarray(data)
            if dtype is None and data.dtype == np.float64:
                data = data.astype(default_jax_dtype())
            data = jnp.asarray(
                data,
                dtype=to_jax_dtype(dtype) if dtype is not None else None,
                device=get_jax_device(place) if place is not None else None)
        elif dtype is not None and data.dtype != to_jax_dtype(dtype):
            data = data.astype(to_jax_dtype(dtype))
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node = None
        self._out_idx = 0
        if name is not None:
            self.name = name  # else lazily generated via __getattr__
        self.persistable = False
        self.trainable = not stop_gradient
        self._grad_hooks = None
        self._spec = None  # optional jax PartitionSpec annotation (distributed)

    def __getattr__(self, attr):
        # unset slots raise AttributeError which routes here: generate
        # tensor names lazily — most op outputs are never asked for one,
        # and the f-string counter shows up in the eager dispatch floor
        if attr == "name":
            n = _next_name()
            self.name = n
            return n
        raise AttributeError(attr)

    # -- basic metadata ----------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    # paddle alias
    @property
    def dim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self) -> DType:
        return _as_dtype(np.dtype(self._data.dtype))

    @property
    def place(self) -> Place:
        d = getattr(self._data, "devices", None)
        if d is None or isinstance(self._data, jax.core.Tracer):
            return TPUPlace(0)
        dev = next(iter(self._data.devices()))
        return CPUPlace() if dev.platform == "cpu" else TPUPlace(dev.id)

    @property
    def is_leaf(self):
        return self._node is None

    def element_size(self):
        return np.dtype(self._data.dtype).itemsize

    @property
    def T(self):
        from . import ops
        return ops.manipulation.transpose(
            self, list(range(self.ndim))[::-1]) if self.ndim > 1 else self

    @property
    def mT(self):
        from . import ops
        if self.ndim < 2:
            raise ValueError("mT requires ndim >= 2")
        perm = list(range(self.ndim))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return ops.manipulation.transpose(self, perm)

    # -- value access ------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        return self._data.item(*args) if args else np.asarray(self._data).item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is "
                "ambiguous (use .any()/.all()).")
        return bool(np.asarray(self._data).item())

    def __int__(self):
        return int(self.item())

    def __float__(self):
        return float(self.item())

    def __index__(self):
        return int(self.item())

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        with np.printoptions(**_np_print_kwargs()):
            return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                    f"{grad_info},\n       {np.asarray(self._data)!r})")

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- autograd surface --------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor] if grad_tensor is not None
                          else None, retain_graph=retain_graph)

    def clear_grad(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad._data = jnp.zeros_like(self._grad._data)
        else:
            self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from . import ops
        return ops.math._unary(jnp.copy, self, name="clone")

    def register_hook(self, hook):
        """Hook called with the gradient when it is produced for this leaf
        (ref: ``paddle.Tensor.register_hook``). Returns a handle with
        ``remove()``."""
        if self._grad_hooks is None:
            self._grad_hooks = {}
        hid = len(self._grad_hooks)
        self._grad_hooks[hid] = hook

        class _Handle:
            def remove(_self):
                self._grad_hooks.pop(hid, None)
        return _Handle()

    # -- conversion / movement --------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from . import ops
        return ops.math.cast(self, dtype)

    cast = astype

    def to(self, *args, **kwargs):
        """Flexible .to(device|dtype|tensor) like the reference."""
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, Place)):
                if isinstance(a, str) and a.replace("paddle_tpu.", "") in \
                        _BY_NAME:
                    out = out.astype(a)
                else:
                    dev = get_jax_device(a if isinstance(a, (str, Place)) else None)
                    out = Tensor(jax.device_put(out._data, dev),
                                 stop_gradient=out.stop_gradient)
            elif isinstance(a, (DType, np.dtype, type)):
                out = out.astype(a)
            elif isinstance(a, Tensor):
                out = out.astype(a.dtype)
        return out

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def tpu(self, device_id=0, blocking=True):
        return Tensor(jax.device_put(self._data, get_jax_device(f"tpu:{device_id}")),
                      stop_gradient=self.stop_gradient)

    cuda = tpu  # parity alias

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx):
        from . import ops
        return ops.manipulation._getitem(self, idx)

    def __setitem__(self, idx, value):
        from . import ops
        ops.manipulation._setitem(self, idx, value)

    # -- in-place value ops (rebind data; graph history of old value kept) --
    def set_value(self, value):
        value = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch {value.shape} vs {self._data.shape}")
        self._data = value.astype(self._data.dtype)
        return self

    def copy_(self, other, non_blocking=False):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    # value_hook for optimizers: raw array access
    @property
    def data(self):
        return self

    @data.setter
    def data(self, value):
        self.set_value(value)

    def _md5sum(self):
        import hashlib
        return hashlib.md5(np.ascontiguousarray(self.numpy()).tobytes()).hexdigest()


class Parameter(Tensor):
    """Trainable tensor (ref: ``python/paddle/fluid/framework.py Parameter``).

    Created by ``Layer.create_parameter``; ``stop_gradient`` defaults False
    and it is ``persistable`` (included in checkpoints).
    """

    __slots__ = ("optimize_attr", "regularizer", "do_model_average",
                 "is_distributed", "need_clip", "_lazy")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name or _next_name("param"))
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.is_distributed = False

    def initialize(self):
        """Run the deferred initializer recorded under ``LazyGuard``
        (ref: ``fluid/lazy_init.py``). No-op for eagerly-created params."""
        lazy = getattr(self, "_lazy", None)
        if lazy is not None:
            init, shape, jdt = lazy
            self._data = jnp.asarray(init(list(shape), jdt))
            self._lazy = None
        return self

    def __repr__(self):
        with np.printoptions(**_np_print_kwargs()):
            return (f"Parameter(name={self.name}, shape={self.shape}, "
                    f"dtype={self.dtype.name}, trainable={self.trainable},\n"
                    f"       {np.asarray(self._data)!r})")


_print_options: dict = {}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor print formatting (ref: ``tensor/to_string.py
    set_printoptions``). Applied inside ``Tensor.__repr__`` only — numpy's
    global state is left alone."""
    if precision is not None:
        _print_options["precision"] = int(precision)
    if threshold is not None:
        _print_options["threshold"] = int(threshold)
    if edgeitems is not None:
        _print_options["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        _print_options["linewidth"] = int(linewidth)
    if sci_mode is not None:
        _print_options["sci_mode"] = _builtins_bool(sci_mode)


_builtins_bool = bool


def _np_print_kwargs() -> dict:
    """Translate the paddle-style options into np.printoptions kwargs.
    sci_mode=True needs an explicit float formatter — numpy's
    ``suppress=False`` is the default and cannot *force* scientific."""
    kw = {k: v for k, v in _print_options.items() if k != "sci_mode"}
    sci = _print_options.get("sci_mode")
    if sci is True:
        prec = _print_options.get("precision", 8)
        kw["formatter"] = {
            "float_kind": lambda v: np.format_float_scientific(
                v, precision=prec)}
    elif sci is False:
        kw["suppress"] = True
    return kw


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """``paddle.to_tensor`` equivalent."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


# -- pytree registration ----------------------------------------------------
def _flatten(t: Tensor):
    return (t._data,), (type(t), t.stop_gradient)


def _unflatten(aux, children):
    cls, stop_gradient = aux
    t = Tensor.__new__(cls)
    Tensor.__init__(t, children[0], stop_gradient=stop_gradient)
    return t


jax.tree_util.register_pytree_node(Tensor, _flatten, _unflatten)
jax.tree_util.register_pytree_node(
    Parameter,
    lambda t: ((t._data,), (Parameter, t.stop_gradient)),
    _unflatten)
