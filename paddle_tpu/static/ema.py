"""ExponentialMovingAverage for static programs (ref:
``python/paddle/static/__init__.py`` → ``incubate/optimizer/...
ExponentialMovingAverage`` in the reference tree).

The reference builds EMA as extra program ops over persistable vars; here
the scope IS the parameter store, so EMA is three scope transforms:
``update()`` folds current params into the shadow dict, ``apply()``
swaps shadows in (context manager), ``restore()`` swaps back.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from .executor import global_scope
from . import graph as G

__all__ = ["ExponentialMovingAverage"]


class ExponentialMovingAverage:
    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._shadow: dict = {}
        self._backup: dict = {}
        self._injected: list = []  # keys set with no prior scope value
        self._dtypes: dict = {}
        self._step = 0

    def _param_keys(self, program):
        program = program or G.default_main_program()
        return [k for k in program.scope_tensors if "@state@" not in k]

    def update(self, program=None):
        """Fold current parameter values into the shadow average. With
        ``thres_steps`` the effective decay warms up like the reference:
        min(decay, (1+steps)/(10+steps))."""
        scope = global_scope()
        d = self._decay
        if self._thres_steps is not None:
            d = min(d, (1.0 + self._step) / (10.0 + self._step))
        for k in self._param_keys(program):
            v = scope.find_var(k)
            if v is None:
                continue
            # accumulate in f32, remember the param dtype for apply()
            cur = np.asarray(v).astype(np.float32)
            self._dtypes[k] = np.asarray(v).dtype
            if k not in self._shadow:
                self._shadow[k] = cur.copy()
            else:
                self._shadow[k] = d * self._shadow[k] + (1.0 - d) * cur
        self._step += 1

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap EMA values into the scope for evaluation."""
        scope = global_scope()
        self._backup = {}
        self._injected = []
        for k, ema_v in self._shadow.items():
            v = scope.find_var(k)
            if v is not None:
                self._backup[k] = v
            else:
                self._injected.append(k)
            dt = self._dtypes.get(k, np.float32)
            scope.set(k, jnp.asarray(ema_v.astype(dt)))
        try:
            yield self
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        scope = global_scope()
        for k, v in self._backup.items():
            scope.set(k, v)
        # keys that had NO scope value before apply() must not linger —
        # a later Executor.run would silently pick up the EMA value
        for k in self._injected:
            scope.vars.pop(k, None)
        self._backup = {}
        self._injected = []
