"""Static-graph persistence (ref: ``python/paddle/static/io.py``).

``save/load``: program parameters + optimizer state from the Scope, pickled
as numpy (same container discipline as ``paddle.save``).

``save_inference_model/load_inference_model``: the reference serializes a
pruned ProgramDesc + persistables; the TPU-native artifact is a **StableHLO
export** of the composed feed→fetch function (via ``jax.export``) plus the
parameter values — the deployment story XLA understands (the
AnalysisPredictor equivalent consumes it in ``paddle_tpu.inference``).
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax
import jax.export  # noqa: F401  (binds the submodule attr; not re-exported on older jax)
import jax.numpy as jnp

from . import graph as G
from .executor import global_scope, Executor

__all__ = ["save", "load", "save_inference_model", "load_inference_model",
           "serialize_program", "serialize_persistables", "save_to_file",
           "deserialize_program", "deserialize_persistables",
           "load_from_file", "normalize_program", "load_program_state",
           "set_program_state"]


def _program_state(program, scope):
    state = {}
    for key, t in program.scope_tensors.items():
        v = scope.find_var(key)
        state[key] = np.asarray(v if v is not None else t._data)
    for key in program.scope_init:
        v = scope.find_var(key)
        if v is not None:
            state[key] = np.asarray(v)
    return state


def save(program, model_path, protocol=4):
    """``paddle.static.save``: parameters → `model_path.pdparams`, optimizer
    state → `model_path.pdopt`."""
    scope = global_scope()
    state = _program_state(program, scope)
    params = {k: v for k, v in state.items() if "@state@" not in k}
    opt = {k: v for k, v in state.items() if "@state@" in k}
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f, protocol=protocol)
    with open(model_path + ".pdopt", "wb") as f:
        pickle.dump(opt, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """``paddle.static.load``: restore scope vars saved by ``save``."""
    scope = global_scope()
    for suffix in (".pdparams", ".pdopt"):
        path = model_path + suffix
        if not os.path.exists(path):
            continue
        with open(path, "rb") as f:
            state = pickle.load(f)
        for k, v in state.items():
            scope.set(k, jnp.asarray(v))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None):
    """Export feed→fetch as serialized StableHLO + params."""
    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]
    if not isinstance(fetch_vars, (list, tuple)):
        fetch_vars = [fetch_vars]
    program = program or (feed_vars[0]._prog if feed_vars else None) \
        or G.default_main_program()
    scope = global_scope()

    from .gradients import _replay_fn
    fetch_vids = [program.resolve(v._vid) for v in fetch_vars]
    # scope keys over all fetches
    all_scope, replays = [], []
    for fv in fetch_vids:
        replay, feed_vids, scope_keys = _replay_fn(program, fv)
        replays.append((replay, feed_vids))
        for k in scope_keys:
            if k not in all_scope:
                all_scope.append(k)
    feed_vid_list = [v._vid for v in feed_vars]

    params = {}
    for k in all_scope:
        v = scope.find_var(k)
        if v is None:
            t = program.scope_tensors.get(k)
            v = t._data if t is not None else jnp.asarray(
                program.scope_init[k]())
        params[k] = v

    def infer_fn(params, *feeds):
        feed_env = dict(zip(feed_vid_list, feeds))
        outs = []
        for replay, fvids in replays:
            missing = [v for v in fvids if v not in feed_env]
            if missing:
                raise ValueError(
                    f"fetch needs feed vids {missing} not among feed_vars")
            outs.append(replay(feed_env, params))
        return tuple(outs)

    # dynamic (-1/None) feed dims export shape-polymorphically so the
    # artifact serves any batch size (ref: the -1 dims a ProgramDesc keeps)
    n_dyn = 0
    dim_strs = []
    for v in feed_vars:
        ds = []
        for d in v._sym_shape:
            if d < 0:
                ds.append(f"_dyn{n_dyn}")
                n_dyn += 1
            else:
                ds.append(str(d))
        dim_strs.append(",".join(ds) if ds else "")
    if n_dyn:
        scope_sym = jax.export.SymbolicScope()
        feed_specs = [
            jax.ShapeDtypeStruct(
                jax.export.symbolic_shape(s, scope=scope_sym) if s else (),
                v._data.dtype)
            for s, v in zip(dim_strs, feed_vars)]
    else:
        feed_specs = [jax.ShapeDtypeStruct(tuple(v._data.shape),
                                           v._data.dtype)
                      for v in feed_vars]
    param_specs = {k: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
                   for k, v in params.items()}
    exported = jax.export.export(jax.jit(infer_fn))(param_specs, *feed_specs)
    blob = exported.serialize()

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({"params": {k: np.asarray(v) for k, v in params.items()},
                     "feed_names": [v.name for v in feed_vars],
                     "fetch_names": [v.name for v in fetch_vars]}, f)


class _LoadedInferenceProgram:
    """Deserialized StableHLO artifact, runnable via Executor.run."""

    def __init__(self, exported, params, feed_names, fetch_names):
        self.exported = exported
        self.params = params
        self.feed_names = feed_names
        self.fetch_names = fetch_names

    def __call__(self, *feeds):
        return self.exported.call(self.params, *feeds)


def load_inference_model(path_prefix, executor=None):
    """Returns (program, feed_names, fetch_names); run the program with
    ``program(*feed_arrays)`` or through ``Executor.run``."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    with open(path_prefix + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    params = {k: jnp.asarray(v) for k, v in meta["params"].items()}
    prog = _LoadedInferenceProgram(exported, params, meta["feed_names"],
                                   meta["fetch_names"])
    return prog, meta["feed_names"], meta["fetch_names"]


# -- program/persistable (de)serialization (ref static/io.py) ---------------
_EXPORT_CACHE: dict = {}


def _export_blob(feed_vars, fetch_vars, program=None):
    """Shared core of save_inference_model/serialize_program: export the
    feed→fetch function to a serialized StableHLO blob + params. The
    canonical call pattern (serialize_program then serialize_persistables
    on the same vars) must not pay the StableHLO trace twice — one-entry
    memo keyed by the exact (feed, fetch, program) identity."""
    import tempfile
    # the params live in the scope and change between calls — include an
    # identity stamp of the current scope values (jax arrays are
    # immutable; scope.set rebinds, changing the ids) so a checkpoint
    # loop never gets stale weights back from the memo
    prog = program or G.default_main_program()
    scope = global_scope()
    stamp = tuple(id(scope.find_var(k)) for k in prog.scope_tensors) \
        if prog is not None else ()
    key = (tuple(id(v) for v in feed_vars),
           tuple(id(v) for v in fetch_vars), id(program), stamp)
    hit = _EXPORT_CACHE.get("entry")
    if hit is not None and hit[0] == key:
        return hit[1], hit[2]
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "m")
        save_inference_model(prefix, feed_vars, fetch_vars, None,
                             program=program)
        with open(prefix + ".pdmodel", "rb") as f:
            model = f.read()
        with open(prefix + ".pdiparams", "rb") as f:
            persist = f.read()
    _EXPORT_CACHE["entry"] = (key, model, persist)
    return model, persist


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """Program -> bytes (the StableHLO export; ref ``static/io.py
    serialize_program`` emits the pruned ProgramDesc proto)."""
    return _export_blob(feed_vars, fetch_vars, program)[0]


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs):
    """Persistable values -> bytes."""
    return _export_blob(feed_vars, fetch_vars, program)[1]


def deserialize_program(data):
    """bytes -> runnable exported program (jax.export artifact)."""
    return jax.export.deserialize(bytearray(data))


def deserialize_persistables(program, data, executor=None):
    """bytes -> {name: array}; also loads them into the global scope so a
    subsequent Executor.run sees the restored values."""
    meta = pickle.loads(data)
    params = {k: jnp.asarray(v) for k, v in meta["params"].items()}
    scope = global_scope()
    for k, v in params.items():
        scope.set(k, v)
    return params


def save_to_file(path, content):
    """Raw bytes → file (ref ``static/io.py save_to_file``)."""
    if not isinstance(content, bytes):
        raise TypeError("save_to_file expects bytes content")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Prune/normalize for export (ref ``static/io.py
    normalize_program``). The TPU export path prunes at StableHLO trace
    time (only ops reachable from the fetches are replayed), so this is
    a validated clone."""
    if not isinstance(program, G.Program):
        raise TypeError("program must be a Program")
    return program.clone()


def load_program_state(model_path, var_list=None):
    """``model_path(.pdparams/.pdopt)`` -> {name: ndarray} without
    touching any scope (ref ``static/io.py load_program_state``)."""
    state = {}
    for suffix in (".pdparams", ".pdopt"):
        p = model_path + suffix
        if os.path.exists(p):
            with open(p, "rb") as f:
                state.update(pickle.load(f))
    if not state:
        raise FileNotFoundError(
            f"no program state at {model_path}(.pdparams/.pdopt)")
    return state


def set_program_state(program, state_dict):
    """Write a ``load_program_state`` dict into the program's scope vars
    (ref ``static/io.py set_program_state``)."""
    scope = global_scope()
    unknown = [k for k in state_dict
               if k not in program.scope_tensors
               and k not in program.scope_init]
    for k, v in state_dict.items():
        scope.set(k, jnp.asarray(v))
    if unknown:
        import warnings
        warnings.warn(
            f"set_program_state: {len(unknown)} keys not tracked by the "
            f"program (first: {unknown[:3]})")
