"""``paddle.static.nn`` layer functions (ref: ``python/paddle/static/nn/``).

In the reference these emit OpDescs + create persistable params in the
startup program. Here each call instantiates the matching ``paddle_tpu.nn``
layer (whose parameters are eager Tensors, auto-registered into the Scope
when an op touches them) and applies it to the symbolic input — one code
path for dygraph and static, the design the reference converged toward.
"""
from __future__ import annotations

import importlib


def _nn_mod():  # lazy so static can import before paddle_tpu.nn
    return importlib.import_module("paddle_tpu.nn")


__all__ = ["fc", "conv2d", "batch_norm", "embedding", "conv2d_transpose"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Paddle fc semantics: dims [num_flatten_dims:] flatten into the
    feature axis, leading dims are preserved in the output."""
    lead = list(x.shape[:num_flatten_dims])
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        if d < 0:
            raise ValueError("fc needs static non-batch (feature) dims")
        in_features *= d
    if len(x.shape) != num_flatten_dims + 1 or \
            x.shape[num_flatten_dims] != in_features:
        from ..ops.manipulation import reshape
        x = reshape(x, [-1, in_features])
    layer = _nn_mod().Linear(in_features, size,
                             weight_attr=weight_attr, bias_attr=bias_attr)
    out = layer(x)
    if len(lead) != 1:
        from ..ops.manipulation import reshape
        out = reshape(out, [(-1 if d < 0 else d) for d in lead] + [size])
    if activation:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    return out


def conv2d(x, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, weight_attr=None, bias_attr=None, name=None,
           act=None, data_format="NCHW"):
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _nn_mod().Conv2D(in_channels, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=weight_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(x)
    if act:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(x, num_filters, filter_size, stride=1, padding=0,
                     weight_attr=None, bias_attr=None, name=None,
                     data_format="NCHW"):
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _nn_mod().Conv2DTranspose(in_channels, num_filters, filter_size,
                                stride=stride, padding=padding,
                                weight_attr=weight_attr, bias_attr=bias_attr,
                                data_format=data_format)
    return layer(x)


def batch_norm(x, momentum=0.9, epsilon=1e-5, data_layout="NCHW",
               is_test=False, name=None):
    ch = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
    layer = _nn_mod().BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                            data_format=data_layout)
    if is_test:
        layer.eval()
    return layer(x)


def embedding(input, size, weight_attr=None, is_sparse=False,
              padding_idx=None, name=None):
    layer = _nn_mod().Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=weight_attr)
    return layer(input)


# -- control flow (ref: python/paddle/static/nn/control_flow.py ---------------
# cond :1253, While/while_loop :1507, case :123?, switch_case) — the
# reference lowers these to ConditionalBlock / While ops interpreted by the
# executor; here they ARE the XLA structured-control-flow primitives
# (lax.cond / lax.while_loop / lax.switch), the compiler-friendly form the
# task maps to on TPU. With a CONCRETE predicate (eager mode) they take the
# Python branch directly, which keeps full tape autograd.

def _tree_arrays(obj):
    from ..tensor import Tensor
    import jax
    return jax.tree.map(
        lambda t: t._data if isinstance(t, Tensor) else t, obj,
        is_leaf=lambda t: isinstance(t, Tensor))


def _tree_tensors(obj, like):
    from ..tensor import Tensor
    import jax
    return jax.tree.map(
        lambda a, t: Tensor(a) if isinstance(t, Tensor) else a, obj, like,
        is_leaf=lambda t: isinstance(t, Tensor))


def _is_traced(x):
    import jax
    from ..tensor import Tensor
    d = x._data if isinstance(x, Tensor) else x
    return isinstance(d, jax.core.Tracer)


def _wrap_arrays(obj):
    """Wrap every array leaf of a lax control-flow output as a Tensor."""
    import jax
    from ..tensor import Tensor
    return jax.tree.map(
        lambda a: Tensor(a)
        if isinstance(a, (jax.Array, jax.core.Tracer)) else a, obj)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """ref ``static/nn/control_flow.py cond``: run ``true_fn()`` or
    ``false_fn()`` by ``pred``. Concrete pred → direct Python branch
    (differentiable on the tape); traced pred → ``lax.cond`` (both
    branches must return matching structures, same contract as the
    reference)."""
    import jax
    from jax import lax
    from ..tensor import Tensor

    false_fn = false_fn or (lambda: None)
    true_fn = true_fn or (lambda: None)
    if not _is_traced(pred):
        p = bool(pred._data if isinstance(pred, Tensor) else pred)
        return true_fn() if p else false_fn()
    p = pred._data if isinstance(pred, Tensor) else pred
    p = p.reshape(()) if getattr(p, "ndim", 0) else p
    # BOTH branches trace inside lax.cond (never pre-executed in the
    # enclosing trace — a domain-guarded op in the unselected branch must
    # not run, or its NaNs poison gradients through 0*nan)
    out_arrays = lax.cond(p,
                          lambda: _tree_arrays(true_fn()),
                          lambda: _tree_arrays(false_fn()))
    return _wrap_arrays(out_arrays)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """ref ``control_flow.py while_loop``: iterate ``body`` while
    ``cond(*vars)`` holds. Concrete entry values still lower through
    ``lax.while_loop`` so the loop compiles to ONE XLA while op instead
    of unrolling (reverse-mode AD through it is not defined — use
    ``lax.scan``-style fixed-length loops for differentiable recurrences,
    the same restriction the compiled reference path has)."""
    from jax import lax
    from ..tensor import Tensor

    loop_vars = list(loop_vars)

    def c(arrs):
        out = cond(*_tree_tensors(arrs, loop_vars))
        out = out._data if isinstance(out, Tensor) else out
        return out.reshape(()) if getattr(out, "ndim", 0) else out

    def b(arrs):
        out = body(*_tree_tensors(arrs, loop_vars))
        if not isinstance(out, (list, tuple)):
            out = [out]
        return _tree_arrays(list(out))

    final = lax.while_loop(c, b, _tree_arrays(loop_vars))
    return _tree_tensors(final, loop_vars)


def case(pred_fn_pairs, default=None, name=None):
    """ref ``control_flow.py case``: first pair whose pred holds wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred0, fn0 = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if default is None and not rest:
        return cond(pred0, fn0, fn0)
    return cond(pred0, fn0,
                (lambda: case(rest, default)) if rest else default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref ``control_flow.py switch_case``: pick a branch by integer
    index (``lax.switch`` when traced; direct call when concrete)."""
    import jax
    from jax import lax
    from ..tensor import Tensor

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    idx = branch_index._data if isinstance(branch_index, Tensor) \
        else branch_index
    if not _is_traced(branch_index):
        i = int(idx)
        for k, f in items:
            if k == i:
                return f()
        if default is not None:
            return default()
        raise ValueError(f"branch index {i} not in {keys} and no default")
    if keys != list(range(len(keys))):
        raise ValueError(
            "traced switch_case requires contiguous 0..N-1 branch keys")
    n_real = len(fns)
    if default is not None:
        fns = fns + [default]
    arr_fns = [(lambda f=f: _tree_arrays(f())) for f in fns]
    if default is not None:
        # out-of-range index selects the default slot (eager parity)
        sel = jax.numpy.where((idx >= 0) & (idx < n_real), idx, n_real)
    else:
        sel = jax.numpy.clip(idx, 0, n_real - 1)
    out = lax.switch(sel.reshape(()), arr_fns)
    return _wrap_arrays(out)


__all__ += ["cond", "while_loop", "case", "switch_case"]
