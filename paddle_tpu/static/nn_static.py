"""``paddle.static.nn`` layer functions (ref: ``python/paddle/static/nn/``).

In the reference these emit OpDescs + create persistable params in the
startup program. Here each call instantiates the matching ``paddle_tpu.nn``
layer (whose parameters are eager Tensors, auto-registered into the Scope
when an op touches them) and applies it to the symbolic input — one code
path for dygraph and static, the design the reference converged toward.
"""
from __future__ import annotations

import importlib


def _nn_mod():  # lazy so static can import before paddle_tpu.nn
    return importlib.import_module("paddle_tpu.nn")


__all__ = ["fc", "conv2d", "batch_norm", "embedding", "conv2d_transpose"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Paddle fc semantics: dims [num_flatten_dims:] flatten into the
    feature axis, leading dims are preserved in the output."""
    lead = list(x.shape[:num_flatten_dims])
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        if d < 0:
            raise ValueError("fc needs static non-batch (feature) dims")
        in_features *= d
    if len(x.shape) != num_flatten_dims + 1 or \
            x.shape[num_flatten_dims] != in_features:
        from ..ops.manipulation import reshape
        x = reshape(x, [-1, in_features])
    layer = _nn_mod().Linear(in_features, size,
                             weight_attr=weight_attr, bias_attr=bias_attr)
    out = layer(x)
    if len(lead) != 1:
        from ..ops.manipulation import reshape
        out = reshape(out, [(-1 if d < 0 else d) for d in lead] + [size])
    if activation:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    return out


def conv2d(x, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, weight_attr=None, bias_attr=None, name=None,
           act=None, data_format="NCHW"):
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _nn_mod().Conv2D(in_channels, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=weight_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(x)
    if act:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(x, num_filters, filter_size, stride=1, padding=0,
                     weight_attr=None, bias_attr=None, name=None,
                     data_format="NCHW"):
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _nn_mod().Conv2DTranspose(in_channels, num_filters, filter_size,
                                stride=stride, padding=padding,
                                weight_attr=weight_attr, bias_attr=bias_attr,
                                data_format=data_format)
    return layer(x)


def batch_norm(x, momentum=0.9, epsilon=1e-5, data_layout="NCHW",
               is_test=False, name=None):
    ch = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
    layer = _nn_mod().BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                            data_format=data_layout)
    if is_test:
        layer.eval()
    return layer(x)


def embedding(input, size, weight_attr=None, is_sparse=False,
              padding_idx=None, name=None):
    layer = _nn_mod().Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=weight_attr)
    return layer(input)


# -- control flow (ref: python/paddle/static/nn/control_flow.py ---------------
# cond :1253, While/while_loop :1507, case :123?, switch_case) — the
# reference lowers these to ConditionalBlock / While ops interpreted by the
# executor; here they ARE the XLA structured-control-flow primitives
# (lax.cond / lax.while_loop / lax.switch), the compiler-friendly form the
# task maps to on TPU. With a CONCRETE predicate (eager mode) they take the
# Python branch directly, which keeps full tape autograd.

def _tree_arrays(obj):
    from ..tensor import Tensor
    import jax
    return jax.tree.map(
        lambda t: t._data if isinstance(t, Tensor) else t, obj,
        is_leaf=lambda t: isinstance(t, Tensor))


def _tree_tensors(obj, like):
    from ..tensor import Tensor
    import jax
    return jax.tree.map(
        lambda a, t: Tensor(a) if isinstance(t, Tensor) else a, obj, like,
        is_leaf=lambda t: isinstance(t, Tensor))


def _is_traced(x):
    import jax
    from ..tensor import Tensor
    d = x._data if isinstance(x, Tensor) else x
    return isinstance(d, jax.core.Tracer)


def _wrap_arrays(obj):
    """Wrap every array leaf of a lax control-flow output as a Tensor."""
    import jax
    from ..tensor import Tensor
    return jax.tree.map(
        lambda a: Tensor(a)
        if isinstance(a, (jax.Array, jax.core.Tracer)) else a, obj)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """ref ``static/nn/control_flow.py cond``: run ``true_fn()`` or
    ``false_fn()`` by ``pred``. Concrete pred → direct Python branch
    (differentiable on the tape); traced pred → ``lax.cond`` (both
    branches must return matching structures, same contract as the
    reference)."""
    import jax
    from jax import lax
    from ..tensor import Tensor

    false_fn = false_fn or (lambda: None)
    true_fn = true_fn or (lambda: None)
    if not _is_traced(pred):
        p = bool(pred._data if isinstance(pred, Tensor) else pred)
        return true_fn() if p else false_fn()
    p = pred._data if isinstance(pred, Tensor) else pred
    p = p.reshape(()) if getattr(p, "ndim", 0) else p
    # BOTH branches trace inside lax.cond (never pre-executed in the
    # enclosing trace — a domain-guarded op in the unselected branch must
    # not run, or its NaNs poison gradients through 0*nan)
    out_arrays = lax.cond(p,
                          lambda: _tree_arrays(true_fn()),
                          lambda: _tree_arrays(false_fn()))
    return _wrap_arrays(out_arrays)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """ref ``control_flow.py while_loop``: iterate ``body`` while
    ``cond(*vars)`` holds. Concrete entry values still lower through
    ``lax.while_loop`` so the loop compiles to ONE XLA while op instead
    of unrolling (reverse-mode AD through it is not defined — use
    ``lax.scan``-style fixed-length loops for differentiable recurrences,
    the same restriction the compiled reference path has)."""
    from jax import lax
    from ..tensor import Tensor

    loop_vars = list(loop_vars)

    def c(arrs):
        out = cond(*_tree_tensors(arrs, loop_vars))
        out = out._data if isinstance(out, Tensor) else out
        return out.reshape(()) if getattr(out, "ndim", 0) else out

    def b(arrs):
        out = body(*_tree_tensors(arrs, loop_vars))
        if not isinstance(out, (list, tuple)):
            out = [out]
        return _tree_arrays(list(out))

    final = lax.while_loop(c, b, _tree_arrays(loop_vars))
    return _tree_tensors(final, loop_vars)


def case(pred_fn_pairs, default=None, name=None):
    """ref ``control_flow.py case``: first pair whose pred holds wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred0, fn0 = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if default is None and not rest:
        return cond(pred0, fn0, fn0)
    return cond(pred0, fn0,
                (lambda: case(rest, default)) if rest else default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref ``control_flow.py switch_case``: pick a branch by integer
    index (``lax.switch`` when traced; direct call when concrete)."""
    import jax
    from jax import lax
    from ..tensor import Tensor

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    idx = branch_index._data if isinstance(branch_index, Tensor) \
        else branch_index
    if not _is_traced(branch_index):
        i = int(idx)
        for k, f in items:
            if k == i:
                return f()
        if default is not None:
            return default()
        raise ValueError(f"branch index {i} not in {keys} and no default")
    if keys != list(range(len(keys))):
        raise ValueError(
            "traced switch_case requires contiguous 0..N-1 branch keys")
    n_real = len(fns)
    if default is not None:
        fns = fns + [default]
    arr_fns = [(lambda f=f: _tree_arrays(f())) for f in fns]
    if default is not None:
        # out-of-range index selects the default slot (eager parity)
        sel = jax.numpy.where((idx >= 0) & (idx < n_real), idx, n_real)
    else:
        sel = jax.numpy.clip(idx, 0, n_real - 1)
    out = lax.switch(sel.reshape(()), arr_fns)
    return _wrap_arrays(out)


__all__ += ["cond", "while_loop", "case", "switch_case"]


# -- remaining static.nn layer wrappers (ref: python/paddle/static/nn/
# common.py) — each builds the layer the dygraph API already provides and
# applies it, the same delegation the reference performs onto nn ops.

def _apply_act(out, act):
    if act:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


def conv3d(x, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, weight_attr=None, bias_attr=None, name=None,
           act=None, data_format="NCDHW"):
    in_channels = x.shape[1] if data_format == "NCDHW" else x.shape[-1]
    layer = _nn_mod().Conv3D(in_channels, num_filters, filter_size,
                             stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             weight_attr=weight_attr, bias_attr=bias_attr,
                             data_format=data_format)
    return _apply_act(layer(x), act)


def conv3d_transpose(x, num_filters, filter_size, stride=1, padding=0,
                     weight_attr=None, bias_attr=None, name=None,
                     act=None, data_format="NCDHW"):
    in_channels = x.shape[1] if data_format == "NCDHW" else x.shape[-1]
    layer = _nn_mod().Conv3DTranspose(in_channels, num_filters,
                                      filter_size, stride=stride,
                                      padding=padding,
                                      weight_attr=weight_attr,
                                      bias_attr=bias_attr,
                                      data_format=data_format)
    return _apply_act(layer(x), act)


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn_mod().GroupNorm(groups, ch, epsilon=epsilon,
                                weight_attr=param_attr,
                                bias_attr=bias_attr,
                                data_format=data_layout)
    return _apply_act(layer(input), act)


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    nd = len(input.shape)
    cls = {3: "InstanceNorm1D", 4: "InstanceNorm2D",
           5: "InstanceNorm3D"}.get(nd)
    if cls is None:
        raise ValueError(f"instance_norm expects 3-5D input, got {nd}D")
    layer = getattr(_nn_mod(), cls)(input.shape[1], epsilon=epsilon,
                                    weight_attr=param_attr,
                                    bias_attr=bias_attr)
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    """Normalize over dims [begin_norm_axis:] (the static-era knob the
    dygraph LayerNorm expresses via normalized_shape)."""
    normalized_shape = list(input.shape[begin_norm_axis:])
    layer = _nn_mod().LayerNorm(
        normalized_shape, epsilon=epsilon,
        weight_attr=param_attr if scale else False,
        bias_attr=bias_attr if shift else False)
    return _apply_act(layer(input), act)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    if mode == "element":
        # per-element slope of shape x.shape[1:] (the nn.PReLU layer
        # only models the per-channel axis)
        from .. import create_parameter
        from ..ops.op_utils import nary
        from ..nn import initializer as I
        import jax.numpy as jnp
        w = create_parameter(list(x.shape[1:]), "float32",
                             attr=param_attr,
                             default_initializer=I.Constant(0.25))
        return nary(lambda d, a: jnp.where(d > 0, d, a * d), [x, w],
                    name="prelu")
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    else:
        raise ValueError("mode must be all/channel/element")
    layer = _nn_mod().PReLU(num_parameters=num, weight_attr=param_attr,
                            data_format=data_format)
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    layer = _nn_mod().SpectralNorm(weight.shape, dim=dim,
                                   power_iters=power_iters, eps=eps)
    return layer(weight)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    layer = _nn_mod().Bilinear(x.shape[-1], y.shape[-1], size,
                               weight_attr=param_attr,
                               bias_attr=bias_attr)
    return _apply_act(layer(x, y), act)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    """ref ``static/nn/common.py deform_conv2d`` (v2 when mask given)."""
    from .. import create_parameter
    from ..vision.ops import deform_conv2d as _dc
    ks = filter_size if isinstance(filter_size, (list, tuple)) else \
        (filter_size, filter_size)
    w = create_parameter([num_filters, x.shape[1] // groups, ks[0], ks[1]],
                         "float32", attr=param_attr)
    b = create_parameter([num_filters], "float32", attr=bias_attr,
                         is_bias=True) if bias_attr is not False else None
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Normalize by ACCUMULATED batch statistics (ref
    ``static/nn/common.py data_norm`` — the CTR-era normalization whose
    stats are summed counters, not EMA): mean = batch_sum/batch_size,
    var likewise; counters update each training pass."""
    from .. import create_parameter
    from ..ops.op_utils import ensure_tensor, nary
    import jax
    import jax.numpy as jnp
    from ..nn import initializer as I
    x = ensure_tensor(input)
    d = x.shape[-1]
    # counters get their own anonymous attrs (the reference builds one
    # distinct ParamAttr per counter; a shared named attr would collide)
    batch_size = create_parameter(
        [d], "float32", default_initializer=I.Constant(1e4))
    batch_sum = create_parameter([d], "float32",
                                 default_initializer=I.Constant(0.0))
    batch_square_sum = create_parameter(
        [d], "float32", default_initializer=I.Constant(1e4))

    def f(xd, n, s, sq):
        mean = s / n
        var = jnp.maximum(sq / n - mean ** 2, 0.0)
        return (xd - mean) / jnp.sqrt(var + epsilon)

    out = nary(f, [x, batch_size, batch_sum, batch_square_sum],
               name="data_norm")
    # summary-counter update each training pass (ref: the op's
    # BatchSize/BatchSum/BatchSquareSum outputs feed back every step);
    # eager host-side accumulate, same mechanism as BN running stats
    if not isinstance(x._data, jax.core.Tracer):
        n_rows = float(x.shape[0])
        batch_size._data = batch_size._data + n_rows
        batch_sum._data = batch_sum._data + x._data.sum(axis=0)
        batch_square_sum._data = (batch_square_sum._data
                                  + (x._data ** 2).sum(axis=0))
    return _apply_act(out, act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (ref ``static/nn/common.py:3327``):
    out[t] = sum_{i=0..k} x[t+i] * w[i], per feature."""
    from .. import create_parameter
    from ..ops.op_utils import nary
    import jax.numpy as jnp
    d = input.shape[-1]
    k = int(future_context_size)
    w = create_parameter([k + 1, d], "float32", attr=param_attr)

    def f(xd, wd):
        pad = [(0, 0)] * xd.ndim
        pad[-2] = (0, k)
        xp = jnp.pad(xd, pad)
        t_axis = xd.ndim - 2
        out = 0.0
        for i in range(k + 1):
            out = out + jnp.take(xp, jnp.arange(i, i + xd.shape[t_axis]),
                                 axis=t_axis) * wd[i]
        return out

    return _apply_act(nary(f, [input, w], name="row_conv"), act)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (ref ``static/nn/common.py
    nce``): binary logistic loss over the true class + uniformly sampled
    negatives — the large-vocab training trick the reference ships a
    CUDA kernel for; one gather + matmul region here."""
    from .. import create_parameter
    from ..ops.op_utils import nary
    from ..framework import random as _random
    import jax
    import jax.numpy as jnp
    d = input.shape[-1]
    weight = create_parameter([num_total_classes, d], "float32",
                              attr=param_attr)
    bias = create_parameter([num_total_classes], "float32", attr=bias_attr,
                            is_bias=True)
    # fresh key per nce() call (each eager training step resamples);
    # a captured static node keeps its build-time key — the same
    # contract every sampling op in this framework has under capture
    key = _random.next_key()

    def f(xd, yd, wd, bd):
        B = xd.shape[0]
        neg = jax.random.randint(key, (B, num_neg_samples), 0,
                                 num_total_classes)
        yid = yd.reshape(B, 1).astype(jnp.int32)
        cls = jnp.concatenate([yid, neg], axis=1)     # (B, 1+S)
        wsel = wd[cls]                                # (B, 1+S, D)
        logits = jnp.einsum("bd,bsd->bs", xd, wsel) + bd[cls]
        labels = jnp.concatenate(
            [jnp.ones((B, 1)), jnp.zeros((B, num_neg_samples))], axis=1)
        loss = jnp.maximum(logits, 0) - logits * labels + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return loss.sum(axis=1, keepdims=True)

    return nary(f, [input, label, weight, bias], name="nce")


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """ref ``static/nn/common.py sparse_embedding``: the PS-backed
    embedding; the TPU build stores the table densely (XLA gather) and
    accepts the PS-era knobs (entry/table_class) for parity."""
    layer = _nn_mod().Embedding(size[0], size[1], padding_idx=padding_idx,
                                weight_attr=param_attr)
    return layer(input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Run a host python function inside the program (ref
    ``static/nn/common.py py_func`` over the py_func op). Eager values
    call ``func`` directly; traced values route through
    ``jax.pure_callback`` with ``out``'s shape/dtype as the result
    template (``out`` is a Variable/Tensor template, as in the
    reference)."""
    import numpy as np
    import jax
    from ..tensor import Tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    datas = [v._data if isinstance(v, Tensor) else v for v in xs]
    if not any(isinstance(d, jax.core.Tracer) for d in datas):
        res = func(*[np.asarray(d) for d in datas])
        res = res if isinstance(res, (list, tuple)) else [res]
        got = [Tensor(np.asarray(r)) for r in res]
        return got if isinstance(out, (list, tuple)) else got[0]
    templates = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
                 for o in outs]

    def cb(*arrs):
        res = func(*[np.asarray(a) for a in arrs])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r) for r in res)

    raw = jax.pure_callback(cb, tuple(templates), *datas)
    got = [Tensor(r) for r in raw]
    return got if isinstance(out, (list, tuple)) else got[0]


__all__ += ["conv3d", "conv3d_transpose", "group_norm", "instance_norm",
            "layer_norm", "prelu", "spectral_norm",
            "bilinear_tensor_product", "deform_conv2d", "data_norm",
            "row_conv", "nce", "sparse_embedding", "py_func"]


from .nn_sequence import *  # noqa: E402,F401,F403
from .nn_sequence import __all__ as _seq_all
__all__ += _seq_all


class StaticRNN:
    """Step-wise RNN builder (ref ``static/nn/control_flow.py
    StaticRNN``): the ``with rnn.step():`` block defines ONE time step;
    the runner unrolls it over dim0 of every ``step_input``.

    TPU-native capture: ops inside the block record tape nodes anyway
    (the funnel), so the block body is captured as the node sequence and
    replayed per step THROUGH the same funnel — under ``to_static`` /
    program capture each replayed step is recorded like hand-written
    code, i.e. the loop unrolls statically (the XLA-friendly form).
    """

    def __init__(self, name=None):
        self._nodes = []          # captured body nodes, creation order
        self._subs = {}           # placeholder id -> role
        self._seq = []            # (ph, full_tensor)
        self._mems = []           # [ph, init_tensor, new_tensor|None]
        self._outs = []
        self._entered = False
        self._done = False

    # -- capture ------------------------------------------------------------
    class _StepCtx:
        def __init__(self, rnn):
            self._rnn = rnn

        def __enter__(self):
            from ..autograd import add_op_observer
            rnn = self._rnn
            rnn._entered = True

            def observe(name, inputs, outputs):
                node = outputs[0]._node if outputs else None
                if node is not None:
                    rnn._nodes.append((node, list(outputs)))
            rnn._observer = observe
            add_op_observer(observe)
            return rnn

        def __exit__(self, *exc):
            from ..autograd import remove_op_observer
            remove_op_observer(self._rnn._observer)
            self._rnn._done = True
            return False

    def step(self):
        return StaticRNN._StepCtx(self)

    def step_input(self, x):
        from ..ops.op_utils import ensure_tensor
        x = ensure_tensor(x)
        ph = x[0]
        # capture rides the tape: placeholders must be tracked so every
        # body op records a Node (carrying the fn the replay needs)
        ph.stop_gradient = False
        self._seq.append((ph, x))
        return ph

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        from ..ops.op_utils import ensure_tensor
        from ..ops.creation import full
        if init is None:
            if shape is None and batch_ref is None:
                raise ValueError("memory() needs init= or shape=/batch_ref=")
            if batch_ref is not None:
                b = ensure_tensor(batch_ref).shape[ref_batch_dim_idx]
                shp = [b] + list(shape or [])
            else:
                shp = list(shape)
            init = full(shp, init_value, "float32")
        mem = ensure_tensor(init)
        mem.stop_gradient = False  # see step_input: capture needs the tape
        self._mems.append([mem, mem, None])
        return mem

    def update_memory(self, mem, x):
        for rec in self._mems:
            if rec[0] is mem:
                rec[2] = x
                return
        raise ValueError("update_memory: unknown memory tensor")

    def step_output(self, o):
        self._outs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    # -- replay -------------------------------------------------------------
    def _replay(self, env):
        """Re-run the captured body with substitutions, THROUGH the op
        funnel (so to_static / program capture sees real ops)."""
        from ..ops.op_utils import nary
        for node, outs in self._nodes:
            if all(id(o) in env for o in outs):
                continue  # substituted producer (step_input slice etc.)
            args = [env.get(id(t), t) for t in node.inputs]
            n_out = len(outs)
            got = nary(node.fn, args, name=node.name, n_out=n_out)
            got = got if isinstance(got, tuple) else (got,)
            for o, g in zip(outs, got):
                env[id(o)] = g
        return env

    def __call__(self):
        from .. import ops
        if not self._done:
            raise RuntimeError("complete the `with rnn.step():` block "
                               "before calling the rnn")
        if not self._seq:
            raise RuntimeError("StaticRNN needs at least one step_input")
        T = self._seq[0][1].shape[0]
        mems = {id(rec[0]): rec[1] for rec in self._mems}
        step_outs = []
        for t in range(T):
            env = dict(mems)
            for ph, full_x in self._seq:
                env[id(ph)] = full_x[t]
            env = self._replay(env)
            step_outs.append([env.get(id(o), o) for o in self._outs])
            mems = {id(rec[0]): (env.get(id(rec[2]), rec[2])
                                 if rec[2] is not None
                                 else mems[id(rec[0])])
                    for rec in self._mems}
        stacked = [ops.stack([row[i] for row in step_outs], axis=0)
                   for i in range(len(self._outs))]
        return stacked[0] if len(stacked) == 1 else stacked


__all__ += ["StaticRNN"]
