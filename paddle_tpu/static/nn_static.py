"""``paddle.static.nn`` layer functions (ref: ``python/paddle/static/nn/``).

In the reference these emit OpDescs + create persistable params in the
startup program. Here each call instantiates the matching ``paddle_tpu.nn``
layer (whose parameters are eager Tensors, auto-registered into the Scope
when an op touches them) and applies it to the symbolic input — one code
path for dygraph and static, the design the reference converged toward.
"""
from __future__ import annotations

import importlib


def _nn_mod():  # lazy so static can import before paddle_tpu.nn
    return importlib.import_module("paddle_tpu.nn")


__all__ = ["fc", "conv2d", "batch_norm", "embedding", "conv2d_transpose"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Paddle fc semantics: dims [num_flatten_dims:] flatten into the
    feature axis, leading dims are preserved in the output."""
    lead = list(x.shape[:num_flatten_dims])
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        if d < 0:
            raise ValueError("fc needs static non-batch (feature) dims")
        in_features *= d
    if len(x.shape) != num_flatten_dims + 1 or \
            x.shape[num_flatten_dims] != in_features:
        from ..ops.manipulation import reshape
        x = reshape(x, [-1, in_features])
    layer = _nn_mod().Linear(in_features, size,
                             weight_attr=weight_attr, bias_attr=bias_attr)
    out = layer(x)
    if len(lead) != 1:
        from ..ops.manipulation import reshape
        out = reshape(out, [(-1 if d < 0 else d) for d in lead] + [size])
    if activation:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    return out


def conv2d(x, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, weight_attr=None, bias_attr=None, name=None,
           act=None, data_format="NCHW"):
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _nn_mod().Conv2D(in_channels, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=weight_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(x)
    if act:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(x, num_filters, filter_size, stride=1, padding=0,
                     weight_attr=None, bias_attr=None, name=None,
                     data_format="NCHW"):
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _nn_mod().Conv2DTranspose(in_channels, num_filters, filter_size,
                                stride=stride, padding=padding,
                                weight_attr=weight_attr, bias_attr=bias_attr,
                                data_format=data_format)
    return layer(x)


def batch_norm(x, momentum=0.9, epsilon=1e-5, data_layout="NCHW",
               is_test=False, name=None):
    ch = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
    layer = _nn_mod().BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                            data_format=data_layout)
    if is_test:
        layer.eval()
    return layer(x)


def embedding(input, size, weight_attr=None, is_sparse=False,
              padding_idx=None, name=None):
    layer = _nn_mod().Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=weight_attr)
    return layer(input)
