"""Static graph core: Program, Variable, the op recorder.

TPU-native re-design of the reference's static-graph layer
(``paddle/fluid/framework/``: ProgramDesc/BlockDesc/OpDesc over protobuf,
``python/paddle/fluid/framework.py`` Program/Block/Variable mirrors):

 - A ``Program`` is a recorded dataflow DAG of pure jax functions — the
   jaxpr/XLA-era replacement for protobuf op descs. No separate
   InferShape pass: output metadata comes from ``jax.eval_shape`` (the
   InferMeta analog, ref ``paddle/phi/infermeta/``), which costs zero FLOPs.
 - ``Variable`` is a symbolic Tensor whose ``_data`` is a
   ``jax.ShapeDtypeStruct``; every existing ``paddle_tpu`` op and ``nn``
   layer works unchanged on Variables because all ops funnel through
   ``autograd.record``, where the recorder hook lives.
 - Parameters stay eager Tensors; when an op touches one, it is registered
   as a scope-resident input (the reference's persistable var in a Scope,
   ref ``paddle/fluid/framework/scope.h``).

Execution lives in ``executor.py``: the whole program compiles to ONE XLA
computation per (feed-shapes, fetch-set) — the standalone-executor
instruction list (``new_executor/interpretercore.h:29``) collapses into the
XLA schedule.
"""
from __future__ import annotations

import contextlib
import threading
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from .. import autograd as _autograd
from ..tensor import Tensor
from ..framework.dtype import to_jax_dtype, DType

__all__ = [
    "Program", "Variable", "program_guard", "default_main_program",
    "default_startup_program", "data", "enable_static", "disable_static",
    "in_static_mode", "name_scope",
]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "progs"):
        _tls.progs = []
    return _tls.progs


_static_mode = False
_default_main: "Program|None" = None
_default_startup: "Program|None" = None


def enable_static():
    """``paddle.enable_static()``."""
    global _static_mode
    _static_mode = True
    # install the recorder only while static mode is on: eager dispatch
    # must not pay a per-op no-op call (bench_eager.py dispatch floor)
    _autograd._static_recorder = _maybe_record


def disable_static():
    """``paddle.disable_static()``."""
    global _static_mode
    _static_mode = False
    _autograd._static_recorder = None


def in_static_mode() -> bool:
    return _static_mode


def default_main_program() -> "Program":
    global _default_main
    if _stack():
        return _stack()[-1][0]
    if _default_main is None:
        _default_main = Program()
    return _default_main


def default_startup_program() -> "Program":
    global _default_startup
    if _stack():
        return _stack()[-1][1]
    if _default_startup is None:
        _default_startup = Program()
        _default_startup._paired_main = weakref.ref(default_main_program())
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """``paddle.static.program_guard`` equivalent."""
    if startup_program is None:
        startup_program = Program()
    startup_program._paired_main = weakref.ref(main_program)
    _stack().append((main_program, startup_program))
    try:
        yield
    finally:
        _stack().pop()


@contextlib.contextmanager
def name_scope(prefix):
    """``paddle.static.name_scope`` — cosmetic grouping (kept for parity)."""
    yield


class Variable(Tensor):
    """Symbolic tensor in a Program (ref: ``framework.py`` Variable /
    ``paddle/fluid/framework/var_desc.h``). ``shape`` reports -1 for dynamic
    dims (specialized per feed at Executor.run)."""

    __slots__ = ("_vid", "_sym_shape", "_prog")

    def __init__(self, shape, dtype, name=None, prog=None):
        shape = list(shape)
        rep = tuple(1 if (d is None or int(d) < 0) else int(d)
                    for d in shape)
        # no super().__init__: _data is metadata, not an array
        self._data = jax.ShapeDtypeStruct(rep, to_jax_dtype(dtype))
        self._sym_shape = [-1 if (d is None or int(d) < 0) else int(d)
                           for d in shape]
        self.stop_gradient = True
        self._grad = None
        self._node = None
        self._out_idx = 0
        self.name = name or f"var_{id(self) & 0xffffff:x}"
        self.persistable = False
        self.trainable = False
        self._grad_hooks = []
        self._spec = None
        self._prog = prog
        self._vid = None  # assigned by Program.add_var

    @property
    def shape(self):
        return list(self._sym_shape)

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' is symbolic (static graph); fetch it "
            "through Executor.run(..., fetch_list=[var]) to get values")

    def item(self, *a):
        self.numpy()

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self._sym_shape}, "
                f"dtype={self._data.dtype})")

    __str__ = __repr__


class Node:
    """One recorded op: a pure jax fn over positional inputs.

    ``in_refs`` entries: ("v", vid) graph variable | ("s", key) scope entry
    (parameter / optimizer state) | ("c", idx) baked constant |
    ("h", idx) host-provided scalar (fetched per run, e.g. current LR).
    ``scope_writes``: [(scope_key, out_index)] — outputs written back to the
    scope after each run (optimizer updates).
    """

    __slots__ = ("fn", "in_refs", "out_vids", "consts", "host_fns",
                 "scope_writes", "name")

    def __init__(self, fn, in_refs, out_vids, consts=(), host_fns=(),
                 scope_writes=(), name=""):
        self.fn = fn
        self.in_refs = in_refs
        self.out_vids = out_vids
        self.consts = list(consts)
        self.host_fns = list(host_fns)
        self.scope_writes = list(scope_writes)
        self.name = name


class Program:
    """Recorded op DAG + var/parameter tables (ref: ProgramDesc)."""

    def __init__(self):
        self.nodes: list[Node] = []
        self.feed_map: dict[str, int] = {}     # data() name -> vid
        self.var_meta: dict[int, Variable] = {}
        self.var_by_name: dict[str, int] = {}
        self.scope_tensors: dict[str, Tensor] = {}  # key -> live param
        self.scope_init: dict[str, object] = {}     # key -> () -> array
        self.alias: dict[int, int] = {}        # vid -> replacement vid
        self.version = 0
        self._var_count = 0
        self._paired_main = None
        self.random_seed = 0

    # -- construction -------------------------------------------------------
    def add_var(self, v: Variable) -> int:
        vid = self._var_count
        self._var_count += 1
        v._vid = vid
        v._prog = self
        self.var_meta[vid] = v
        self.var_by_name[v.name] = vid
        self.version += 1
        return vid

    def add_node(self, node: Node):
        self.nodes.append(node)
        self.version += 1

    def register_param(self, t: Tensor) -> str:
        key = t.name
        if key not in self.scope_tensors:
            self.scope_tensors[key] = t
            self.version += 1
        return key

    def register_scope_init(self, key: str, init_fn):
        self.scope_init[key] = init_fn
        self.version += 1

    # -- queries ------------------------------------------------------------
    def resolve(self, vid: int) -> int:
        while vid in self.alias:
            vid = self.alias[vid]
        return vid

    def subgraph_to(self, vids):
        """Nodes (in order) needed to compute `vids`, plus the feed vids and
        scope keys they consume."""
        producer = {}
        for n in self.nodes:
            for ov in n.out_vids:
                producer[ov] = n
        needed_nodes, seen_nodes = [], set()
        feed_vids, scope_keys = set(), []
        scope_seen = set()
        # iterative DFS — deep programs (thousands of sequential ops) must
        # not hit Python's recursion limit
        stack = [self.resolve(v) for v in reversed(vids)]
        while stack:
            vid = stack.pop()
            n = producer.get(vid)
            if n is None:
                feed_vids.add(vid)
                continue
            if id(n) in seen_nodes:
                continue
            seen_nodes.add(id(n))
            needed_nodes.append(n)
            for r in n.in_refs:
                if r[0] == "v":
                    stack.append(r[1])
                elif r[0] == "s" and r[1] not in scope_seen:
                    scope_seen.add(r[1])
                    scope_keys.append(r[1])
        # preserve program order
        order = {id(n): i for i, n in enumerate(self.nodes)}
        needed_nodes.sort(key=lambda n: order[id(n)])
        return needed_nodes, feed_vids, scope_keys

    def global_block(self):
        return _BlockShim(self)

    def list_vars(self):
        return list(self.var_meta.values())

    def clone(self, for_test=False):
        p = Program.__new__(Program)
        p.__dict__ = dict(self.__dict__)
        p.nodes = list(self.nodes)
        p.feed_map = dict(self.feed_map)
        p.var_meta = dict(self.var_meta)
        p.var_by_name = dict(self.var_by_name)
        p.scope_tensors = dict(self.scope_tensors)
        p.scope_init = dict(self.scope_init)
        p.alias = dict(self.alias)
        if for_test:
            # drop training-only nodes (optimizer updates / grad nodes) so
            # evaluating the clone never writes the scope (ref:
            # Program.clone(for_test=True) pruning backward+optimize ops)
            p.nodes = [n for n in p.nodes if not n.scope_writes]
            produced = {ov for n in p.nodes for ov in n.out_vids}
            p.alias = {k: v for k, v in p.alias.items() if v in produced}
        return p

    def __repr__(self):
        return (f"Program(nodes={len(self.nodes)}, "
                f"vars={len(self.var_meta)}, "
                f"params={list(self.scope_tensors)})")


class _BlockShim:
    """Minimal Block facade (``Program.global_block().var(name)``)."""

    def __init__(self, prog):
        self._prog = prog

    def var(self, name):
        vid = self._prog.var_by_name.get(name)
        if vid is None:
            raise ValueError(f"no variable named '{name}'")
        return self._prog.var_meta[vid]

    def all_parameters(self):
        return list(self._prog.scope_tensors.values())


def data(name, shape, dtype="float32", lod_level=0):
    """``paddle.static.data`` — declare a feed placeholder."""
    prog = default_main_program()
    v = Variable(shape, dtype, name=name, prog=prog)
    prog.add_var(v)
    prog.feed_map[name] = v._vid
    return v


# ---------------------------------------------------------------------------
# Recorder hook (installed into autograd.record's dispatch)
# ---------------------------------------------------------------------------
_NOT_STATIC = object()


def _spec_of(t: Tensor):
    d = t._data
    return jax.ShapeDtypeStruct(tuple(d.shape), d.dtype)


def _maybe_record(fn, tensors, outputs_wrap, name):
    """Called by autograd.record first. Returns _NOT_STATIC to fall through
    to eager execution (not in static mode / no symbolic inputs)."""
    if not _static_mode:
        return _NOT_STATIC
    if not any(isinstance(t, Variable) for t in tensors):
        return _NOT_STATIC  # initializers etc. stay eager
    prog = None
    for t in tensors:
        if isinstance(t, Variable) and t._prog is not None:
            prog = t._prog
            break
    if prog is None:
        prog = default_main_program()

    in_refs, specs, consts = [], [], []
    for t in tensors:
        if isinstance(t, Variable):
            in_refs.append(("v", t._vid))
            specs.append(_spec_of(t))
        elif t.persistable or getattr(t, "trainable", False) or \
                not t.stop_gradient:
            key = prog.register_param(t)
            in_refs.append(("s", key))
            specs.append(_spec_of(t))
        else:
            in_refs.append(("c", len(consts)))
            consts.append(t._data)
            specs.append(_spec_of(t))

    out_struct = jax.eval_shape(fn, *specs)
    single = isinstance(out_struct, jax.ShapeDtypeStruct)
    outs_struct = [out_struct] if single else list(out_struct)

    # dynamic-dim propagation: probe with a second representative size for
    # every dynamic input dim; output dims that change are dynamic (-1)
    dyn_struct = None
    if any(isinstance(t, Variable) and -1 in t._sym_shape for t in tensors):
        specs2 = []
        for t, sp in zip(tensors, specs):
            if isinstance(t, Variable) and -1 in t._sym_shape:
                # probe with rep+1 (never a constant — the rep size itself
                # may equal the constant and mask the dynamic dim)
                shape2 = tuple(d + 1 if sd == -1 else d
                               for sd, d in zip(t._sym_shape, sp.shape))
                specs2.append(jax.ShapeDtypeStruct(shape2, sp.dtype))
            else:
                specs2.append(sp)
        try:
            probe = jax.eval_shape(fn, *specs2)
            dyn_struct = [probe] if isinstance(
                probe, jax.ShapeDtypeStruct) else list(probe)
        except Exception:
            dyn_struct = None  # op requires concrete dims; treat as static

    out_vars = []
    for i, st in enumerate(outs_struct):
        sym = list(st.shape)
        if dyn_struct is not None:
            sym = [-1 if d1 != d2 else d1
                   for d1, d2 in zip(st.shape, dyn_struct[i].shape)]
        v = Variable(sym, "float32", prog=prog)
        v._data = jax.ShapeDtypeStruct(tuple(st.shape), st.dtype)
        v._sym_shape = sym
        prog.add_var(v)
        out_vars.append(v)
    prog.add_node(Node(fn, in_refs, [v._vid for v in out_vars],
                       consts=consts, name=name))
    return out_vars[0] if single else tuple(out_vars)


_autograd._STATIC_SENTINEL = _NOT_STATIC
