"""Static-graph Executor: one jitted XLA program per (feed, fetch) shape.

TPU-native replacement for the reference's standalone executor stack
(``StandaloneExecutor`` ``new_executor/standalone_executor.cc:28``,
``InterpreterCore``/``ProgramInterpreter`` instruction scheduling,
``_ExecutorCache`` ``python/paddle/fluid/executor.py:701``):

 - "Convert program → instruction list + dependency/stream analysis" becomes
   "compose the node DAG into one pure function and ``jax.jit`` it" — XLA
   owns scheduling, fusion, streams, and memory planning.
 - The compile cache is keyed on (program version, feed shapes/dtypes,
   fetch set), the analog of `_ExecutorCache`'s (program, scope) key.
 - Scope semantics (``paddle/fluid/framework/scope.h``): persistable
   parameters and optimizer state live in a Scope dict across runs; update
   nodes declare scope writes, applied after each run from the jitted
   program's donated outputs.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from . import graph as G

__all__ = ["Executor", "Scope", "global_scope", "scope_guard",
           "CompiledProgram"]


class Scope:
    """name -> jax.Array container (ref: ``scope.h``)."""

    def __init__(self):
        self.vars: dict[str, jax.Array] = {}

    def find_var(self, name):
        return self.vars.get(name)

    def var(self, name):
        return self.vars.setdefault(name, None)

    def set(self, name, value):
        self.vars[name] = value

    def drop_kids(self):
        self.vars.clear()


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope() -> Scope:
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope: Scope):
        self.scope = scope

    def __enter__(self):
        _scope_stack.append(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _scope_stack.pop()
        return False


class CompiledProgram:
    """Parity shim (``paddle.static.CompiledProgram``): every program is
    compiled here, so this only carries the underlying program through."""

    def __init__(self, program, build_strategy=None):
        self.program = program


class Executor:
    def __init__(self, place=None):
        self.place = place
        self._cache: dict = {}

    # -- startup -----------------------------------------------------------
    def _run_startup(self, program: "G.Program", scope: Scope):
        main = program._paired_main() if program._paired_main else None
        progs = [p for p in (main, program) if p is not None]
        for p in progs:
            for key, t in p.scope_tensors.items():
                scope.set(key, t._data)
            for key, init in p.scope_init.items():
                scope.set(key, jnp.asarray(init()))
        return []

    # -- main --------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        if isinstance(program, CompiledProgram):
            program = program.program
        program = program or G.default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []

        if not program.nodes and not fetch_list:  # startup program
            return self._run_startup(program, scope)

        fetch_vids = tuple(self._fetch_vid(program, f) for f in fetch_list)
        if not program.nodes:
            # no ops: fetches can only be feed placeholders
            by_vid = {vid: name for name, vid in program.feed_map.items()}
            out = []
            for vid in fetch_vids:
                name = by_vid.get(vid)
                if name is None or name not in feed:
                    raise KeyError("fetch of an unfed placeholder in an "
                                   "empty program")
                val = feed[name]
                out.append(np.asarray(val._data if isinstance(val, Tensor)
                                      else val))
            return out if return_numpy else [Tensor(o) for o in out]
        feed_names = tuple(sorted(feed))
        feed_arrays = {}
        for name in feed_names:
            val = feed[name]
            if isinstance(val, Tensor):
                val = val._data
            vid = program.feed_map.get(name)
            if vid is None:
                raise KeyError(f"feed '{name}' is not a data() var of this "
                               f"program (has {list(program.feed_map)})")
            want = program.var_meta[vid]._data.dtype
            feed_arrays[name] = jnp.asarray(val, dtype=want)

        key = (id(program), program.version, fetch_vids,
               tuple((n, feed_arrays[n].shape, str(feed_arrays[n].dtype))
                     for n in feed_names))
        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            # the entry holds the program reference: id(program) in the key
            # must stay valid for as long as the cache line lives
            entry = self._compile(program, feed_names, fetch_vids) \
                + (program,)
            if use_program_cache:
                self._cache[key] = entry
        fn, scope_keys, write_keys, host_fns = entry[:4]

        # materialize scope inputs (implicit startup for missing params)
        scope_vals = []
        for k in scope_keys:
            v = scope.find_var(k)
            if v is None:
                t = program.scope_tensors.get(k)
                if t is not None:
                    v = t._data
                elif k in program.scope_init:
                    v = jnp.asarray(program.scope_init[k]())
                else:
                    raise KeyError(f"scope var '{k}' has no value and no "
                                   "initializer; run the startup program")
                scope.set(k, v)
            scope_vals.append(v)

        host_vals = tuple(jnp.asarray(hf(), jnp.float32) for hf in host_fns)
        fetches, writes = fn(tuple(feed_arrays[n] for n in feed_names),
                             tuple(scope_vals), host_vals)
        for k, v in zip(write_keys, writes):
            scope.set(k, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def _fetch_vid(self, program, f):
        if isinstance(f, str):
            vid = program.var_by_name.get(f)
            if vid is None:
                raise KeyError(f"no variable named '{f}' to fetch")
            return program.resolve(vid)
        if isinstance(f, G.Variable):
            return program.resolve(f._vid)
        raise TypeError(f"fetch_list entries must be Variable or str, "
                        f"got {type(f)}")

    def _compile(self, program: "G.Program", feed_names, fetch_vids):
        """Dead-node-eliminated composition of the DAG into one jittable fn.
        Nodes with scope writes always run (they ARE the training step)."""
        write_nodes = [n for n in program.nodes if n.scope_writes]
        target_vids = list(fetch_vids) + [ov for n in write_nodes
                                          for ov in n.out_vids]
        needed, _, _ = program.subgraph_to(target_vids)
        needed_set = {id(n) for n in needed} | {id(n) for n in write_nodes}
        nodes = [n for n in program.nodes if id(n) in needed_set]

        scope_keys, host_fns = [], []
        for n in nodes:
            for r in n.in_refs:
                if r[0] == "s" and r[1] not in scope_keys:
                    scope_keys.append(r[1])
            for hf in n.host_fns:
                host_fns.append(hf)
        write_keys = list(dict.fromkeys(
            k for n in nodes for (k, _) in n.scope_writes))

        feed_vid_of = dict(program.feed_map)

        def composed(feed_tuple, scope_tuple, host_tuple):
            env = {}
            for name, arr in zip(feed_names, feed_tuple):
                env[feed_vid_of[name]] = arr
            scope_env = dict(zip(scope_keys, scope_tuple))
            hi = 0
            writes = {}
            for n in nodes:
                args = []
                for r in n.in_refs:
                    kind, ref = r
                    if kind == "v":
                        args.append(env[ref])
                    elif kind == "s":
                        args.append(scope_env[ref])
                    elif kind == "c":
                        args.append(n.consts[ref])
                    else:  # "h"
                        args.append(host_tuple[hi])
                        hi += 1
                out = n.fn(*args)
                outs = (out,) if not isinstance(out, (tuple, list)) else out
                for vid, o in zip(n.out_vids, outs):
                    env[vid] = o
                for skey, oidx in n.scope_writes:
                    writes[skey] = outs[oidx]
                    scope_env[skey] = outs[oidx]  # later nodes see the update
            fetches = tuple(env[v] for v in fetch_vids)
            return fetches, tuple(writes[k] for k in write_keys)

        jitted = jax.jit(composed)
        return jitted, scope_keys, tuple(write_keys), host_fns
