"""Static-mode autodiff: append_backward / gradients / optimizer.minimize.

The reference builds backward by emitting per-op grad OpDescs
(``python/paddle/fluid/backward.py`` append_backward) then running them
through the executor. Here the recorded forward subgraph is replayed inside
``jax.value_and_grad`` as ONE node — XLA differentiates and fuses the whole
step, and its CSE merges the replay with any forward nodes fetched alongside
(so fetching loss + running minimize costs one forward, not two).

``minimize`` additionally folds the optimizer update
(``Optimizer.apply_gradients_tree``) into the same node, with optimizer
state living in the Scope (the reference's persistable accumulators,
ref ``python/paddle/optimizer/optimizer.py`` _create_accumulators) and the
current LR passed per run as a host scalar (so LRScheduler steps don't
recompile).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import graph as G

__all__ = ["append_backward", "gradients", "append_minimize"]


def _replay_fn(prog: "G.Program", loss_vid: int):
    """Build pure fn(feed_env: dict vid->arr, scope_env: dict key->arr)
    -> loss array, plus the feed vids / scope keys it needs."""
    nodes, feed_vids, scope_keys = prog.subgraph_to([loss_vid])
    loss_vid = prog.resolve(loss_vid)

    def replay(feed_env, scope_env):
        env = dict(feed_env)
        for n in nodes:
            args = []
            for kind, ref in n.in_refs:
                if kind == "v":
                    args.append(env[ref])
                elif kind == "s":
                    args.append(scope_env[ref])
                elif kind == "c":
                    args.append(n.consts[ref])
                else:
                    raise RuntimeError(
                        "cannot differentiate through a host-input node; "
                        "call minimize before adding dependent nodes")
            out = n.fn(*args)
            outs = (out,) if not isinstance(out, (tuple, list)) else out
            for vid, o in zip(n.out_vids, outs):
                # setdefault: values injected into feed_env (e.g. gradients
                # w.r.t. an intermediate var) must stay connected — the
                # producing node must not overwrite them
                if vid not in env:
                    env[vid] = o
        return env[loss_vid]

    return replay, sorted(feed_vids), scope_keys


def _feed_refs(feed_vids):
    return [("v", v) for v in feed_vids]


def gradients(targets, inputs, target_gradients=None):
    """``paddle.static.gradients``: grads of targets wrt input vars, seeded
    with ``target_gradients`` cotangents (ones by default)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    if len(target_gradients) != len(targets):
        raise ValueError("target_gradients must match targets in length")
    prog = targets[0]._prog or G.default_main_program()

    # one replay per target (each over its own subgraph)
    replays, feed_set, scope_keys = [], set(), []
    for t in targets:
        rp, fv, sk = _replay_fn(prog, t._vid)
        replays.append(rp)
        feed_set |= set(fv)
        for k in sk:
            if k not in scope_keys:
                scope_keys.append(k)
    in_vids = [v._vid for v in inputs]
    feed_set |= set(in_vids)
    # cotangent vars are extra graph inputs
    tg_vids = [tg._vid for tg in target_gradients
               if isinstance(tg, G.Variable)]
    feed_vids = sorted(feed_set | set(tg_vids))

    n_feed = len(feed_vids)

    def grad_node_fn(*datas):
        feed_env = dict(zip(feed_vids, datas[:n_feed]))
        scope_env = dict(zip(scope_keys, datas[n_feed:]))

        def loss_of(wrt_vals):
            fe = dict(feed_env)
            fe.update(dict(zip(in_vids, wrt_vals)))
            total = None
            for rp, tg in zip(replays, target_gradients):
                out = rp(dict(fe), scope_env)
                if isinstance(tg, G.Variable):
                    out = out * feed_env[tg._vid]
                elif tg is not None:
                    out = out * jnp.asarray(
                        tg._data if hasattr(tg, "_data") else tg)
                contrib = jnp.sum(out)
                total = contrib if total is None else total + contrib
            return total

        grads = jax.grad(loss_of)([feed_env[v] for v in in_vids])
        return tuple(grads)

    in_refs = _feed_refs(feed_vids) + [("s", k) for k in scope_keys]
    out_vars = []
    for v in inputs:
        gv = G.Variable(list(v._data.shape), "float32", prog=prog,
                        name=f"{v.name}@GRAD")
        gv._data = jax.ShapeDtypeStruct(tuple(v._data.shape), v._data.dtype)
        gv._sym_shape = list(v._data.shape)
        prog.add_var(gv)
        out_vars.append(gv)
    prog.add_node(G.Node(grad_node_fn, in_refs, [v._vid for v in out_vars],
                         name="gradients"))
    return out_vars


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """``paddle.static.append_backward``: returns [(param, grad_var)]."""
    prog = loss._prog or G.default_main_program()
    replay, feed_vids, scope_keys = _replay_fn(prog, loss._vid)
    params = {k: t for k, t in ((k, prog.scope_tensors[k])
                                for k in scope_keys)
              if getattr(t, "trainable", True) and not t.stop_gradient}
    if parameter_list is not None:
        names = {p.name if hasattr(p, "name") else p for p in parameter_list}
        params = {k: t for k, t in params.items() if k in names}
    if no_grad_set:
        params = {k: t for k, t in params.items() if k not in no_grad_set}
    pkeys = list(params)
    n_feed = len(feed_vids)

    def bwd_fn(*datas):
        feed_env = dict(zip(feed_vids, datas[:n_feed]))
        scope_env = dict(zip(scope_keys, datas[n_feed:]))

        def loss_of(pvals):
            se = dict(scope_env)
            se.update(dict(zip(pkeys, pvals)))
            return replay(feed_env, se)

        grads = jax.grad(loss_of)([scope_env[k] for k in pkeys])
        return tuple(grads)

    in_refs = _feed_refs(feed_vids) + [("s", k) for k in scope_keys]
    out = []
    for k in pkeys:
        t = params[k]
        gv = G.Variable(list(t._data.shape), "float32", prog=prog,
                        name=f"{k}@GRAD")
        gv._data = jax.ShapeDtypeStruct(tuple(t._data.shape), t._data.dtype)
        gv._sym_shape = list(t._data.shape)
        prog.add_var(gv)
        out.append((t, gv))
    prog.add_node(G.Node(bwd_fn, in_refs, [gv._vid for (_, gv) in out],
                         name="append_backward"))
    return out


def append_minimize(optimizer, loss, parameters=None):
    """Record the fused backward+update node for ``optimizer.minimize(loss)``
    in static mode. Parameters and optimizer state update in the Scope."""
    prog = loss._prog or G.default_main_program()
    replay, feed_vids, scope_keys = _replay_fn(prog, loss._vid)

    params = {}
    for k in scope_keys:
        t = prog.scope_tensors.get(k)
        if t is not None and getattr(t, "trainable", True) \
                and not t.stop_gradient:
            params[k] = t
    if parameters is not None:
        names = {p.name if hasattr(p, "name") else p for p in parameters}
        params = {k: t for k, t in params.items() if k in names}
    pkeys = list(params)

    # optimizer state: initialized into the scope by the startup program
    state0 = optimizer.init_state_tree(
        {k: t._data for k, t in params.items()})
    state_leaves, state_def = jax.tree_util.tree_flatten(state0)
    opt_tag = f"opt_{id(optimizer) & 0xffffff:x}"
    skeys = [f"{opt_tag}@state@{i}" for i in range(len(state_leaves))]
    for key, leaf in zip(skeys, state_leaves):
        prog.register_scope_init(key, (lambda v=leaf: v))

    all_scope = list(dict.fromkeys(scope_keys + skeys))
    n_feed = len(feed_vids)

    def update_fn(lr, *datas):
        feed_env = dict(zip(feed_vids, datas[:n_feed]))
        rest = datas[n_feed:]
        scope_env = dict(zip(all_scope, rest[:len(all_scope)]))
        state = jax.tree_util.tree_unflatten(
            state_def, [scope_env[k] for k in skeys])

        def loss_of(pvals):
            se = dict(scope_env)
            se.update(pvals)
            return replay(feed_env, se)

        pdict = {k: scope_env[k] for k in pkeys}
        loss_val, grads = jax.value_and_grad(loss_of)(pdict)
        new_params, new_state = optimizer.apply_gradients_tree(
            pdict, grads, state, lr=lr)
        new_leaves = jax.tree_util.tree_leaves(new_state)
        return (loss_val, *[new_params[k] for k in pkeys], *new_leaves)

    in_refs = ([("h", 0)] + _feed_refs(feed_vids)
               + [("s", k) for k in all_scope])
    loss_out = G.Variable([], "float32", prog=prog,
                          name=f"{loss.name}@MIN")
    loss_out._data = jax.ShapeDtypeStruct((), loss._data.dtype)
    loss_out._sym_shape = []
    prog.add_var(loss_out)
    out_vids = [loss_out._vid]
    scope_writes = []
    for i, k in enumerate(pkeys):
        t = params[k]
        pv = G.Variable(list(t._data.shape), "float32", prog=prog,
                        name=f"{k}@NEW")
        pv._data = jax.ShapeDtypeStruct(tuple(t._data.shape), t._data.dtype)
        pv._sym_shape = list(t._data.shape)
        prog.add_var(pv)
        out_vids.append(pv._vid)
        scope_writes.append((k, i + 1))
    for i, k in enumerate(skeys):
        leaf = state_leaves[i]
        sv = G.Variable(list(leaf.shape), "float32", prog=prog,
                        name=f"{k}@NEW")
        sv._data = jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype)
        sv._sym_shape = list(leaf.shape)
        prog.add_var(sv)
        out_vids.append(sv._vid)
        scope_writes.append((k, len(pkeys) + 1 + i))

    prog.add_node(G.Node(update_fn, in_refs, out_vids,
                         host_fns=[optimizer.get_lr],
                         scope_writes=scope_writes, name="minimize"))
    # fetching the original loss var rides the fused node (XLA CSE would
    # merge anyway; the alias avoids even building the standalone path)
    prog.alias[loss._vid] = loss_out._vid
    return None, [(params[k], None) for k in pkeys]
