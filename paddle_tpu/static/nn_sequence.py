"""LoD sequence ops (ref: ``python/paddle/static/nn/sequence_lod.py``).

The reference's LoDTensor carries level-of-detail offsets inside the
tensor; this build's Tensors are plain arrays, so the lod lives in a
weak side registry: :func:`set_lod` attaches ``[len_0, len_1, ...]`` to
a tensor (``paddle_tpu.static.data(..., lod_level=1)`` feeds do it for
you), sequence ops read it, and every op re-attaches the proper lod to
its output. A tensor with no lod is one sequence — the same degenerate
rule the reference applies to plain Tensors.
"""
from __future__ import annotations

import weakref

import numpy as np
import jax.numpy as jnp

from ..ops.op_utils import ensure_tensor, nary

__all__ = [
    "set_lod", "get_lod", "sequence_conv", "sequence_softmax",
    "sequence_pool", "sequence_concat", "sequence_first_step",
    "sequence_last_step", "sequence_slice", "sequence_expand",
    "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_reshape", "sequence_scatter", "sequence_enumerate",
    "sequence_reverse",
]

# id(tensor) -> np.ndarray of sequence lengths; weakref.finalize evicts
# (Tensor.__eq__ returns a Tensor, so a WeakKeyDictionary would trip on
# bucket equality — identity keys avoid that entirely)
_lods: dict = {}


def set_lod(tensor, lengths):
    t = ensure_tensor(tensor)
    lens = np.asarray(lengths, np.int64).ravel()
    if int(lens.sum()) != t.shape[0]:
        raise ValueError(
            f"lod lengths sum to {int(lens.sum())} but dim0 is "
            f"{t.shape[0]}")
    _lods[id(t)] = lens
    weakref.finalize(t, _lods.pop, id(t), None)
    return t


def get_lod(tensor):
    t = ensure_tensor(tensor)
    lens = _lods.get(id(t))
    if lens is None:
        return np.asarray([t.shape[0]], np.int64)  # one sequence
    return lens


def _offsets(lens):
    return np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)


def sequence_softmax(input, use_cudnn=False, name=None):
    """Softmax within each sequence over dim0 (ref
    ``sequence_lod.py sequence_softmax``)."""
    x = ensure_tensor(input)
    lens = get_lod(x)
    off = _offsets(lens)
    seg = np.repeat(np.arange(len(lens)), lens)

    def f(d):
        flat = d.reshape(d.shape[0])
        mx = jnp.asarray([flat[off[i]:off[i + 1]].max()
                          for i in range(len(lens))])
        e = jnp.exp(flat - mx[seg])
        z = jnp.zeros(len(lens)).at[seg].add(e)
        return (e / z[seg]).reshape(d.shape)

    return set_lod(nary(f, [x], name="sequence_softmax"), lens)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    x = ensure_tensor(input)
    lens = get_lod(x)
    off = _offsets(lens)
    pool_type = pool_type.lower()
    seg = np.repeat(np.arange(len(lens)), lens)
    n = len(lens)

    def f(d):
        if pool_type in ("sum", "average", "sqrt"):
            z = jnp.zeros((n,) + d.shape[1:], d.dtype).at[seg].add(d)
            if pool_type == "average":
                z = z / jnp.maximum(jnp.asarray(lens, d.dtype), 1
                                    ).reshape((n,) + (1,) * (d.ndim - 1))
            elif pool_type == "sqrt":
                z = z / jnp.sqrt(jnp.maximum(
                    jnp.asarray(lens, d.dtype), 1)).reshape(
                        (n,) + (1,) * (d.ndim - 1))
        elif pool_type == "max":
            z = jnp.full((n,) + d.shape[1:], -jnp.inf, d.dtype) \
                .at[seg].max(d)
        elif pool_type == "first":
            z = d[jnp.asarray(off[:-1])]
        elif pool_type == "last":
            z = d[jnp.asarray(off[1:] - 1)]
        else:
            raise ValueError(f"unknown pool_type {pool_type!r}")
        empty = jnp.asarray(lens == 0).reshape(
            (n,) + (1,) * (d.ndim - 1))
        return jnp.where(empty, jnp.asarray(pad_value, d.dtype), z)

    return nary(f, [x], name="sequence_pool")


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_concat(input, name=None):
    """Concatenate the i-th sequences of every input (ref
    ``sequence_concat``): out lod_i = sum of input lod_i."""
    xs = [ensure_tensor(v) for v in input]
    lods = [get_lod(v) for v in xs]
    n = len(lods[0])
    if any(len(l) != n for l in lods):
        raise ValueError("sequence_concat inputs need equal seq counts")
    offs = [_offsets(l) for l in lods]
    order = []  # (input idx, start, stop) in output order
    for i in range(n):
        for j, off in enumerate(offs):
            order.append((j, int(off[i]), int(off[i + 1])))

    def f(*ds):
        return jnp.concatenate([ds[j][a:b] for j, a, b in order], axis=0)

    out_lens = np.sum(np.stack(lods), axis=0)
    return set_lod(nary(f, xs, name="sequence_concat"), out_lens)


def sequence_slice(input, offset, length, name=None):
    x = ensure_tensor(input)
    lens = get_lod(x)
    off = _offsets(lens)
    o = np.asarray(ensure_tensor(offset)._data).ravel()
    ln = np.asarray(ensure_tensor(length)._data).ravel()
    spans = [(int(off[i] + o[i]), int(off[i] + o[i] + ln[i]))
             for i in range(len(lens))]
    for i, (a, b) in enumerate(spans):
        if a < off[i] or b > off[i + 1]:
            raise ValueError(
                f"sequence_slice out of range for sequence {i}")

    def f(d):
        return jnp.concatenate([d[a:b] for a, b in spans], axis=0)

    return set_lod(nary(f, [x], name="sequence_slice"), ln)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat x's i-th sequence len(y_i) times (ref
    ``sequence_expand``)."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    xl = get_lod(xt)
    yl = get_lod(yt)
    off = _offsets(xl)
    idx = []
    out_lens = []
    for i, reps in enumerate(yl):
        for _ in range(int(reps)):
            idx.extend(range(int(off[i]), int(off[i + 1])))
            out_lens.append(int(xl[i]))
    gather = jnp.asarray(np.asarray(idx, np.int64))
    out = nary(lambda d: d[gather], [xt], name="sequence_expand")
    return set_lod(out, out_lens)


def sequence_expand_as(x, y, name=None):
    """Expand each x ROW to the length of y's i-th sequence (ref
    ``sequence_expand_as``: x has one row per y sequence)."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    yl = get_lod(yt)
    if xt.shape[0] != len(yl):
        raise ValueError("sequence_expand_as: x rows must equal y's "
                         "sequence count")
    gather = jnp.asarray(np.repeat(np.arange(len(yl)), yl))
    out = nary(lambda d: d[gather], [xt], name="sequence_expand_as")
    return set_lod(out, yl)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Pack sequences into (num_seq, maxlen, ...) + lengths (ref
    ``sequence_pad``); returns (out, length)."""
    from ..tensor import Tensor
    xt = ensure_tensor(x)
    pv = ensure_tensor(pad_value)
    lens = get_lod(xt)
    off = _offsets(lens)
    m = int(maxlen) if maxlen is not None else int(lens.max())
    if (lens > m).any():
        raise ValueError(f"maxlen {m} shorter than longest sequence")
    n = len(lens)
    rows = np.concatenate([np.full(int(l), i) for i, l in
                           enumerate(lens)]) if n else np.zeros(0, int)
    cols = np.concatenate([np.arange(int(l)) for l in lens]) if n else \
        np.zeros(0, int)

    def f(d, p):
        buf = jnp.broadcast_to(p.astype(d.dtype),
                               (n, m) + d.shape[1:]).copy() \
            if p.ndim else jnp.full((n, m) + d.shape[1:], p, d.dtype)
        return buf.at[rows, cols].set(d)

    out = nary(f, [xt, pv], name="sequence_pad")
    return out, Tensor(jnp.asarray(lens))


def sequence_unpad(x, length, name=None):
    xt = ensure_tensor(x)
    lens = np.asarray(ensure_tensor(length)._data).ravel()
    rows = np.concatenate([np.full(int(l), i) for i, l in
                           enumerate(lens)])
    cols = np.concatenate([np.arange(int(l)) for l in lens])

    def f(d):
        return d[rows, cols]

    return set_lod(nary(f, [xt], name="sequence_unpad"), lens)


def sequence_reshape(input, new_dim, name=None):
    x = ensure_tensor(input)
    lens = get_lod(x)
    d = x.shape[-1]
    total = lens * d
    if (total % new_dim).any():
        raise ValueError("each sequence's total elements must divide "
                         "new_dim")
    out_lens = total // new_dim
    out = nary(lambda a: a.reshape(-1, new_dim), [x],
               name="sequence_reshape")
    return set_lod(out, out_lens)


def sequence_scatter(input, index, updates, name=None):
    """Scatter-add updates into input at per-sequence positions (ref
    ``sequence_scatter``: index is a lod tensor of positions local to
    each sequence; input rows correspond to sequences)."""
    xt = ensure_tensor(input)
    it = ensure_tensor(index)
    ut = ensure_tensor(updates)
    ilens = get_lod(it)
    rows = np.repeat(np.arange(len(ilens)), ilens)

    def f(d, i, u):
        return d.at[rows, i.reshape(-1).astype(jnp.int32)].add(u)

    return nary(f, [xt, it, ut], name="sequence_scatter")


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding windows of ids within each sequence (ref
    ``sequence_enumerate``): out[i] = ids[i:i+win], padded past each
    sequence end."""
    x = ensure_tensor(input)
    lens = get_lod(x)
    off = _offsets(lens)
    idx = np.zeros((int(lens.sum()), win_size), np.int64)
    valid = np.zeros_like(idx, dtype=bool)
    r = 0
    for i, l in enumerate(lens):
        for j in range(int(l)):
            for k in range(win_size):
                if j + k < int(l):
                    idx[r, k] = off[i] + j + k
                    valid[r, k] = True
            r += 1

    def f(d):
        flat = d.reshape(d.shape[0])
        out = flat[jnp.asarray(idx)]
        return jnp.where(jnp.asarray(valid), out,
                         jnp.asarray(pad_value, d.dtype))

    return set_lod(nary(f, [x], name="sequence_enumerate"), lens)


def sequence_reverse(x, name=None):
    xt = ensure_tensor(x)
    lens = get_lod(xt)
    off = _offsets(lens)
    perm = np.concatenate([np.arange(int(off[i + 1]) - 1,
                                     int(off[i]) - 1, -1)
                           for i in range(len(lens))]) if len(lens) else \
        np.zeros(0, int)
    gather = jnp.asarray(perm)
    return set_lod(nary(lambda d: d[gather], [xt],
                        name="sequence_reverse"), lens)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window convolution over each sequence (ref
    ``sequence_conv``): each step's window of ``filter_size`` rows
    (centered per ``padding_start``, zero-padded at sequence edges)
    flattens and passes through one (filter_size*D, num_filters)
    projection."""
    from ..ops.creation import create_parameter
    x = ensure_tensor(input)
    lens = get_lod(x)
    off = _offsets(lens)
    d = x.shape[-1]
    if filter_stride != 1:
        raise ValueError("sequence_conv supports filter_stride=1 "
                         "(reference kernel restriction)")
    start = -int((filter_size - 1) // 2) if padding_start is None \
        else int(padding_start)
    w = create_parameter([filter_size * d, num_filters], "float32",
                         attr=param_attr)
    b = create_parameter([num_filters], "float32", attr=bias_attr,
                         is_bias=True) if bias_attr is not False else None
    # window gather indices: -1 marks a zero pad slot
    tot = int(lens.sum())
    idx = np.full((tot, filter_size), -1, np.int64)
    for i in range(len(lens)):
        for j in range(int(lens[i])):
            r = int(off[i]) + j
            for k in range(filter_size):
                p = j + start + k
                if 0 <= p < int(lens[i]):
                    idx[r, k] = off[i] + p
    gather = jnp.asarray(np.maximum(idx, 0))
    mask = jnp.asarray((idx >= 0)[..., None])

    args = [x, w] + ([b] if b is not None else [])

    def f(dd, wd, *rest):
        win = jnp.where(mask, dd[gather], 0.0)       # (tot, k, D)
        flat = win.reshape(dd.shape[0], filter_size * d)
        out = flat @ wd
        return out + rest[0] if rest else out

    out = nary(f, args, name="sequence_conv")
    out = set_lod(out, lens)
    if act:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out
