"""``paddle.static`` — static-graph API surface.

TPU-native redesign of the reference static stack (see graph.py /
executor.py / gradients.py docstrings for the mapping):
ProgramDesc→recorded jax-fn DAG, InterpreterCore→one jitted XLA program,
append_backward→`jax.value_and_grad` over the replayed subgraph,
inference model→StableHLO export.

Ref entry points: ``python/paddle/static/``, Executor
``python/paddle/fluid/executor.py:895``.
"""
from .graph import (  # noqa: F401
    Program, Variable, program_guard, default_main_program,
    default_startup_program, data, name_scope,
)
from .executor import (  # noqa: F401
    Executor, Scope, global_scope, scope_guard, CompiledProgram,
)
from .gradients import append_backward, gradients  # noqa: F401
from .io import (  # noqa: F401
    save, load, save_inference_model, load_inference_model,
    serialize_program, serialize_persistables, save_to_file,
    deserialize_program, deserialize_persistables, load_from_file,
    normalize_program, load_program_state, set_program_state,
)
from .ema import ExponentialMovingAverage  # noqa: F401
from . import nn_static as nn  # noqa: F401
from ..framework.device import device_guard, CPUPlace, TPUPlace  # noqa: F401
from ..ops.creation import create_parameter  # noqa: F401


def cpu_places(device_count=None):
    """ref ``static/__init__.py cpu_places``."""
    import os as _os
    n = device_count or int(_os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Parity alias: the accelerator places on this build are TPU chips."""
    import jax as _jax
    if device_ids is None:
        device_ids = range(len(_jax.devices()))
    return [TPUPlace(i) for i in device_ids]


xpu_places = cuda_places


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Scope-resident constant var (ref ``tensor/creation.py
    create_global_var``)."""
    from ..tensor import Tensor
    from ..framework.dtype import to_jax_dtype
    import jax.numpy as _jnp
    data = _jnp.full(tuple(int(s) for s in shape), value,
                     to_jax_dtype(dtype))
    t = Tensor(data, name=name)
    t.persistable = persistable
    prog = default_main_program()
    if prog is not None and persistable:
        prog.register_param(t)
    return t


def Print(input, first_n=-1, message=None, summarize=20, **kwargs):
    """Debug print pass-through (ref ``static/nn/control_flow.py Print``);
    eager/traced-safe via jax.debug.print."""
    import jax
    from ..ops.op_utils import unary
    msg = message or ""

    def f(d):
        # debug.callback, not debug.print: the message is user text, not
        # a format spec (braces in it must print literally)
        jax.debug.callback(lambda arr: print(msg, arr), d)
        return d
    return unary(f, input, name="print")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-callback op (ref ``static/nn/common.py py_func``): runs a
    python function over tensor values via pure_callback."""
    import jax
    from ..ops.op_utils import nary, ensure_tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
             for o in outs]

    def f(*datas):
        res = jax.pure_callback(
            lambda *arrs: func(*arrs), specs if len(specs) > 1 else specs[0],
            *datas)
        return res
    return nary(f, [ensure_tensor(v) for v in xs], name="py_func",
                n_out=len(specs))


def accuracy(input, label, k=1, correct=None, total=None):
    """Batch top-k accuracy (ref ``static/nn/metric.py accuracy``)."""
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC (ref ``static/nn/metric.py auc``) — returns the AUC
    value computed over this batch."""
    import numpy as _np
    from ..tensor import Tensor
    from ..metric import Auc
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    preds = _np.asarray(input._data)
    if preds.ndim == 1:
        preds = _np.stack([1 - preds, preds], axis=1)
    m.update(preds, _np.asarray(label._data))
    return Tensor(_np.asarray(m.accumulate(), _np.float32))


class BuildStrategy:
    """Graph-build options holder (ref ``BuildStrategy`` pybind). On TPU
    the XLA pipeline subsumes the pass toggles — attributes are accepted
    and recorded so reference scripts run unchanged."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        if k.startswith("_"):
            raise AttributeError(k)
        return self._opts.get(k)


class ExecutionStrategy(BuildStrategy):
    """Executor options holder (ref ``ExecutionStrategy`` pybind)."""


from ..nn.layer.layers import ParamAttr as _ParamAttr  # noqa: E402


class WeightNormParamAttr(_ParamAttr):
    """Weight-normalized parameter attribute (ref
    ``static/param_attr.py WeightNormParamAttr``). Records ``dim``; the
    reparameterization itself rides ``nn.utils.weight_norm``."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """LR schedule factory (legacy ``static`` spelling, ref
    ``layers/learning_rate_scheduler.py exponential_decay``):
    lr * decay_rate^(step/decay_steps), floored per interval when
    ``staircase``. Returns the dygraph/static-unified scheduler form."""
    import math as _math
    from ..optimizer.lr import LambdaDecay

    def factor(step):
        t = step / float(decay_steps)
        if staircase:
            t = _math.floor(t)
        return decay_rate ** t

    return LambdaDecay(learning_rate=learning_rate, lr_lambda=factor)

InputSpec = None  # set below (shared with jit)
try:
    from ..jit.api import InputSpec  # noqa: F401,F811
except Exception:
    pass

__all__ = [
    "Program", "Variable", "program_guard", "default_main_program",
    "default_startup_program", "data", "name_scope", "Executor", "Scope",
    "global_scope", "scope_guard", "CompiledProgram", "append_backward",
    "gradients", "save", "load", "save_inference_model",
    "load_inference_model", "nn", "InputSpec",
    "serialize_program", "serialize_persistables", "save_to_file",
    "deserialize_program", "deserialize_persistables", "load_from_file",
    "normalize_program", "load_program_state", "set_program_state",
    "ExponentialMovingAverage", "device_guard", "create_parameter",
    "cpu_places", "cuda_places", "xpu_places", "create_global_var",
    "Print", "py_func", "accuracy", "auc", "BuildStrategy",
    "ExecutionStrategy", "WeightNormParamAttr", "exponential_decay",
]
