"""``paddle.static`` — static-graph API surface.

TPU-native redesign of the reference static stack (see graph.py /
executor.py / gradients.py docstrings for the mapping):
ProgramDesc→recorded jax-fn DAG, InterpreterCore→one jitted XLA program,
append_backward→`jax.value_and_grad` over the replayed subgraph,
inference model→StableHLO export.

Ref entry points: ``python/paddle/static/``, Executor
``python/paddle/fluid/executor.py:895``.
"""
from .graph import (  # noqa: F401
    Program, Variable, program_guard, default_main_program,
    default_startup_program, data, name_scope,
)
from .executor import (  # noqa: F401
    Executor, Scope, global_scope, scope_guard, CompiledProgram,
)
from .gradients import append_backward, gradients  # noqa: F401
from .io import (  # noqa: F401
    save, load, save_inference_model, load_inference_model,
)
from . import nn_static as nn  # noqa: F401

InputSpec = None  # set below (shared with jit)
try:
    from ..jit.api import InputSpec  # noqa: F401,F811
except Exception:
    pass

__all__ = [
    "Program", "Variable", "program_guard", "default_main_program",
    "default_startup_program", "data", "name_scope", "Executor", "Scope",
    "global_scope", "scope_guard", "CompiledProgram", "append_backward",
    "gradients", "save", "load", "save_inference_model",
    "load_inference_model", "nn", "InputSpec",
]
