"""Weight-decay regularizers (ref: ``python/paddle/regularizer.py``).

Applied by the optimizer inside the fused update kernel — there is no
separate regularization op pass like the reference's append_regularization.
"""

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    pass


class L2Decay(WeightDecayRegularizer):
    pass
