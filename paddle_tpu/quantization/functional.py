"""Fake-quant primitives with straight-through gradients."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops.op_utils import unary

__all__ = ["fake_quant", "quant_dequant"]


def _qdq(d, scale, bit_length, channel_axis=None):
    bound = float(2 ** (bit_length - 1) - 1)
    s = jnp.asarray(scale)
    if channel_axis is not None and s.ndim == 1:
        shape = [1] * d.ndim
        shape[channel_axis] = -1
        s = s.reshape(shape)
    s = jnp.maximum(s, 1e-9)
    q = jnp.clip(jnp.round(d / s * bound), -bound, bound) * s / bound
    # straight-through estimator: identity gradient through the rounding
    return d + jax.lax.stop_gradient(q - d)


def quant_dequant(x, scale, bit_length=8, channel_axis=None):
    """Simulated symmetric quantize-dequantize (the fake_quantize_dequantize
    op family, ref ``paddle/phi/kernels/fake_quantize_*``)."""
    if isinstance(x, Tensor):
        return unary(lambda d: _qdq(d, scale, bit_length, channel_axis), x,
                     name="quant_dequant")
    return _qdq(jnp.asarray(x), scale, bit_length, channel_axis)


fake_quant = quant_dequant
