"""QAT driver (ref: ``python/paddle/quantization/qat.py`` QAT.quantize /
convert)."""
from __future__ import annotations

from .wrapper import wrap_quanted, QuantedLinear, QuantedConv2D
from .functional import quant_dequant

__all__ = ["QAT"]


def _walk_and_wrap(model, make_wrappers):
    from ..nn.layer.layers import Layer
    for name, sub in list(model._sub_layers.items()):
        if sub is None:
            continue
        wrapped = make_wrappers(sub)
        if wrapped is not None:
            model._sub_layers[name] = wrapped
        else:
            _walk_and_wrap(sub, make_wrappers)
    return model


class QAT:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        """Insert fake-quant (quanter) wrappers per the QuantConfig."""
        if not inplace:
            import copy
            memo = {}
            model = copy.deepcopy(model, memo)
            # layer-identity configs must follow their layers into the copy
            self._config.translate_ids(memo)

        def make(layer):
            act_proto, w_proto = self._config.config_for(layer)
            if act_proto is None and w_proto is None:
                return None
            act = act_proto._instance(layer) if act_proto else None
            w = w_proto._instance(layer) if w_proto else None
            return wrap_quanted(layer, act, w)

        return _walk_and_wrap(model, make)

    def convert(self, model, inplace=False):
        """Fold quanters into static scales: weights become
        quantize-dequantized constants, wrappers collapse to plain layers
        carrying ``quant_scale`` metadata (the deploy form; ref
        ``qat.py convert``)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def fold(m):
            for name, sub in list(m._sub_layers.items()):
                if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                    inner = sub._inner
                    if sub.weight_quanter is not None:
                        inner.weight.set_value(quant_dequant(
                            inner.weight,
                            sub.weight_quanter.scales(),
                            sub.weight_quanter.bit_length(),
                            sub.weight_quanter._observer.quant_axis()))
                    if sub.activation_quanter is not None:
                        inner.quant_scale = \
                            sub.activation_quanter.scales()
                        inner.quant_bits = \
                            sub.activation_quanter.bit_length()
                    m._sub_layers[name] = inner
                elif sub is not None:
                    fold(sub)

        fold(model)
        return model
