"""Observers: scale statistics collectors (ref:
``python/paddle/quantization/observers/abs_max.py`` and the imperative
``moving_average_abs_max``/``hist`` observers)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor

__all__ = ["BaseObserver", "AbsmaxObserver", "MovingAverageAbsmaxObserver",
           "HistObserver", "PerChannelAbsmaxObserver"]


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x,
                      dtype=np.float32)


class BaseObserver:
    """Collects statistics on tensors passing through; yields a scale."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._scale = None

    def observe(self, x):
        raise NotImplementedError

    def scales(self):
        return self._scale if self._scale is not None else 1e-9

    def bit_length(self):
        return self.quant_bits

    def quant_axis(self):
        return None

    # factory protocol used by QuantConfig
    def _instance(self, layer):
        return type(self)(quant_bits=self.quant_bits)


class AbsmaxObserver(BaseObserver):
    def observe(self, x):
        m = float(np.max(np.abs(_np(x))))
        self._scale = m if self._scale is None else max(self._scale, m)


class MovingAverageAbsmaxObserver(BaseObserver):
    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def observe(self, x):
        m = float(np.max(np.abs(_np(x))))
        if self._scale is None:
            self._scale = m
        else:
            self._scale = (self.moving_rate * self._scale
                           + (1 - self.moving_rate) * m)

    def _instance(self, layer):
        return type(self)(quant_bits=self.quant_bits,
                          moving_rate=self.moving_rate)


class HistObserver(BaseObserver):
    """Percentile-of-histogram scale (a lightweight KL-free calibrator)."""

    def __init__(self, quant_bits=8, bins=2048, percentile=0.999):
        super().__init__(quant_bits)
        self.bins = bins
        self.percentile = percentile
        self._hist = None
        self._max = 0.0

    def observe(self, x):
        a = np.abs(_np(x)).ravel()
        m = float(a.max()) if a.size else 0.0
        if self._hist is not None and m > self._max:
            # range grew: redistribute old counts into the new binning by
            # bin-center value, otherwise old magnitudes are inflated
            old_centers = (np.arange(self.bins) + 0.5) / self.bins * self._max
            new_max = max(m, 1e-9)
            new_idx = np.minimum(
                (old_centers / new_max * self.bins).astype(np.int64),
                self.bins - 1)
            rebinned = np.zeros(self.bins, np.float64)
            np.add.at(rebinned, new_idx, self._hist)
            self._hist = rebinned
            self._max = new_max
        elif self._hist is None:
            self._max = max(m, 1e-9)
        hist, _ = np.histogram(a, bins=self.bins, range=(0, self._max))
        if self._hist is None:
            self._hist = hist.astype(np.float64)
        else:
            self._hist += hist
        cdf = np.cumsum(self._hist) / self._hist.sum()
        idx = int(np.searchsorted(cdf, self.percentile))
        self._scale = (idx + 1) / self.bins * self._max

    def _instance(self, layer):
        return type(self)(quant_bits=self.quant_bits, bins=self.bins,
                          percentile=self.percentile)


class PerChannelAbsmaxObserver(BaseObserver):
    def __init__(self, quant_bits=8, quant_axis_=0):
        super().__init__(quant_bits)
        self._axis = quant_axis_

    def observe(self, x):
        a = _np(x)
        axes = tuple(i for i in range(a.ndim) if i != self._axis)
        m = np.max(np.abs(a), axis=axes)
        self._scale = m if self._scale is None else np.maximum(
            self._scale, m)

    def quant_axis(self):
        return self._axis

    def _instance(self, layer):
        return type(self)(quant_bits=self.quant_bits,
                          quant_axis_=self._axis)
