"""PTQ driver (ref: ``python/paddle/quantization/ptq.py``): insert
observers, calibrate on sample batches, convert to quantized weights."""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .functional import quant_dequant
from .wrapper import QuantedLinear, QuantedConv2D
from .qat import _walk_and_wrap

__all__ = ["PTQ"]


class _ObservedLayer(Layer):
    """Runs the inner layer while observing input activations + weights."""

    def __init__(self, inner, act_observer, weight_observer):
        super().__init__()
        self._inner = inner
        self._act_obs = act_observer
        self._w_obs = weight_observer

    def forward(self, *args, **kwargs):
        if self._act_obs is not None and args:
            self._act_obs.observe(args[0])
        if self._w_obs is not None and hasattr(self._inner, "weight"):
            self._w_obs.observe(self._inner.weight)
        return self._inner(*args, **kwargs)


class PTQ:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            memo = {}
            model = copy.deepcopy(model, memo)
            self._config.translate_ids(memo)

        def make(layer):
            # only observe quantizable leaves — containers must be recursed
            # into, not wrapped whole (their inner Linear/Conv would never
            # be observed)
            from ..nn import Linear, Conv2D
            if not isinstance(layer, (Linear, Conv2D)):
                return None
            act_proto, w_proto = self._config.config_for(layer)
            if act_proto is None and w_proto is None:
                return None
            act = act_proto._instance(layer) if act_proto else None
            w = w_proto._instance(layer) if w_proto else None
            return _ObservedLayer(layer, act, w)

        return _walk_and_wrap(model, make)

    def convert(self, model, inplace=False):
        """Apply calibrated scales: quant-dequant weights, attach activation
        scales for the deploy pass."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def fold(m):
            for name, sub in list(m._sub_layers.items()):
                if isinstance(sub, _ObservedLayer):
                    inner = sub._inner
                    if sub._w_obs is not None and \
                            getattr(inner, "weight", None) is not None:
                        inner.weight.set_value(quant_dequant(
                            inner.weight, sub._w_obs.scales(),
                            sub._w_obs.bit_length(),
                            sub._w_obs.quant_axis()))
                    if sub._act_obs is not None:
                        inner.quant_scale = sub._act_obs.scales()
                        inner.quant_bits = sub._act_obs.bit_length()
                    m._sub_layers[name] = inner
                elif sub is not None:
                    fold(sub)

        fold(model)
        return model
