"""Quantized layer wrappers (ref: ``python/paddle/quantization/wrapper.py``
and imperative quant layers ``quantization/imperative/qat.py``
QuantizedLinear/QuantizedConv2D)."""
from __future__ import annotations

from ..nn.layer.layers import Layer
import paddle_tpu.nn.functional as F

__all__ = ["QuantedLinear", "QuantedConv2D", "wrap_quanted"]


class QuantedLinear(Layer):
    def __init__(self, layer, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = layer
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        return F.linear(x, w, self._inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = layer
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        inner = self._inner
        w = inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        return F.conv2d(x, w, inner.bias, stride=inner._stride,
                        padding=inner._padding, dilation=inner._dilation,
                        groups=inner._groups,
                        data_format=inner._data_format)


def wrap_quanted(layer, act_quanter, weight_quanter):
    from ..nn import Linear, Conv2D
    if isinstance(layer, Linear):
        return QuantedLinear(layer, act_quanter, weight_quanter)
    if isinstance(layer, Conv2D):
        return QuantedConv2D(layer, act_quanter, weight_quanter)
    return None
