"""``paddle.quantization`` — QAT / PTQ.

TPU-native re-design of the reference quantization stack
(``python/paddle/quantization/``: QuantConfig/QAT/PTQ/observers/quanters,
imperative fake-quant layers in ``quantization/imperative/``):

 - fake-quant uses the straight-through estimator expressed as
   ``x + stop_gradient(q(x) - x)`` — AD-framework-native (works under
   eager vjp, jit and pjit alike), replacing the reference's dedicated
   fake_quantize CUDA kernels (``paddle/phi/kernels/gpu/quantize_linear*``).
 - observers are host-side stat trackers (abs-max / moving-average /
   histogram-percentile), applied per-tensor or per-channel.
 - int8 simulation: scales from observers, symmetric quant, dequant on the
   fly — the XLA graph stays bf16/fp32 with quant ops fused in.
"""
from .config import QuantConfig  # noqa: F401
from .observers import (  # noqa: F401
    BaseObserver, AbsmaxObserver, MovingAverageAbsmaxObserver,
    HistObserver, PerChannelAbsmaxObserver,
)
from .quanters import (  # noqa: F401
    BaseQuanter, FakeQuanterWithAbsMaxObserver,
    FakeQuanterChannelWiseAbsMaxObserver, quanter,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .wrapper import QuantedLinear, QuantedConv2D  # noqa: F401
from .functional import fake_quant, quant_dequant  # noqa: F401

__all__ = [
    "QuantConfig", "QAT", "PTQ", "BaseObserver", "AbsmaxObserver",
    "MovingAverageAbsmaxObserver", "HistObserver",
    "PerChannelAbsmaxObserver", "BaseQuanter",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterChannelWiseAbsMaxObserver",
    "quanter", "QuantedLinear", "QuantedConv2D", "fake_quant",
    "quant_dequant",
]
