"""QuantConfig (ref: ``python/paddle/quantization/config.py``): maps layers
/ layer types / layer names to activation+weight quanter prototypes.

Entries are structured (not opaque predicates) so QAT/PTQ can translate
layer identities through the deepcopy they perform when ``inplace=False``.
"""
from __future__ import annotations

__all__ = ["QuantConfig"]


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._default_act = activation
        self._default_weight = weight
        # entries: {"kind": "layers"|"types"|"names", "payload", act, weight}
        self._entries = []

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        self._entries.append({"kind": "layers",
                              "payload": {id(l) for l in layers},
                              "act": activation, "weight": weight})

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = tuple(layer_type) if isinstance(layer_type, (list, tuple)) \
            else (layer_type,)
        self._entries.append({"kind": "types", "payload": types,
                              "act": activation, "weight": weight})

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = set(layer_name if isinstance(layer_name, (list, tuple))
                    else [layer_name])
        self._entries.append({"kind": "names", "payload": names,
                              "act": activation, "weight": weight})

    def translate_ids(self, memo):
        """After ``copy.deepcopy(model, memo)``, rewrite layer-identity
        entries to the copied objects (memo maps id(original) -> copy)."""
        for e in self._entries:
            if e["kind"] == "layers":
                e["payload"] = {id(memo[oid]) for oid in e["payload"]
                                if oid in memo} | e["payload"]

    def config_for(self, layer):
        """(act_quanter, weight_quanter) prototypes for this layer, or
        (None, None) if unquantized."""
        for e in self._entries:
            kind, payload = e["kind"], e["payload"]
            if kind == "layers" and id(layer) in payload:
                return e["act"], e["weight"]
            if kind == "types" and isinstance(layer, payload):
                return e["act"], e["weight"]
            if kind == "names":
                name = layer.full_name() if hasattr(layer, "full_name") \
                    else ""
                if name in payload:
                    return e["act"], e["weight"]
        if self._default_act is not None or self._default_weight is not None:
            return self._default_act, self._default_weight
        return None, None
