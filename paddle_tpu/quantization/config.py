"""QuantConfig (ref: ``python/paddle/quantization/config.py``): maps layers
/ layer types to activation+weight quanter prototypes."""
from __future__ import annotations

__all__ = ["QuantConfig"]


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._default_act = activation
        self._default_weight = weight
        self._layer_configs = []   # (predicate, act, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        ids = {id(l) for l in layers}
        self._layer_configs.append(
            (lambda l: id(l) in ids, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = tuple(layer_type) if isinstance(layer_type, (list, tuple)) \
            else (layer_type,)
        self._layer_configs.append(
            (lambda l: isinstance(l, types), activation, weight))

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) \
            else [layer_name]
        self._layer_configs.append(
            (lambda l: getattr(l, "_full_name", "") in names,
             activation, weight))

    def config_for(self, layer):
        """(act_quanter, weight_quanter) prototypes for this layer, or
        (None, None) if unquantized."""
        for pred, act, w in self._layer_configs:
            if pred(layer):
                return act, w
        if self._default_act is not None or self._default_weight is not None:
            return self._default_act, self._default_weight
        return None, None
