"""Quanters: trainable fake-quant modules for QAT (ref:
``python/paddle/quantization/quanters/abs_max.py``
FakeQuanterWithAbsMaxObserver)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor
from .functional import quant_dequant
from .observers import (AbsmaxObserver, MovingAverageAbsmaxObserver,
                        PerChannelAbsmaxObserver)

__all__ = ["BaseQuanter", "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterChannelWiseAbsMaxObserver", "quanter"]


class BaseQuanter:
    """Observes then fake-quantizes; used inside Quanted* wrappers."""

    observer_cls = MovingAverageAbsmaxObserver

    def __init__(self, quant_bits=8, **kw):
        self.quant_bits = quant_bits
        self._observer = self.observer_cls(quant_bits=quant_bits, **kw)

    def __call__(self, x):
        self._observer.observe(x)
        return quant_dequant(x, self._observer.scales(), self.quant_bits,
                             self._observer.quant_axis())

    def scales(self):
        return self._observer.scales()

    def bit_length(self):
        return self.quant_bits

    def _instance(self, layer):
        return type(self)(quant_bits=self.quant_bits)


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    observer_cls = MovingAverageAbsmaxObserver

    def __init__(self, moving_rate=0.9, quant_bits=8, dtype="float32",
                 name=None):
        self.quant_bits = quant_bits
        self._observer = MovingAverageAbsmaxObserver(
            quant_bits=quant_bits, moving_rate=moving_rate)
        self._moving_rate = moving_rate

    def _instance(self, layer):
        return type(self)(moving_rate=self._moving_rate,
                          quant_bits=self.quant_bits)


class FakeQuanterChannelWiseAbsMaxObserver(BaseQuanter):
    def __init__(self, quant_bits=8, quant_axis=0, dtype="float32",
                 name=None):
        self.quant_bits = quant_bits
        self._observer = PerChannelAbsmaxObserver(quant_bits=quant_bits,
                                                  quant_axis_=quant_axis)
        self._axis = quant_axis

    def _instance(self, layer):
        return type(self)(quant_bits=self.quant_bits,
                          quant_axis=self._axis)


def quanter(name):
    """Factory-registration decorator (ref ``factory.py quanter``); kept for
    API parity — classes register under ``quanters.<name>``."""
    def deco(cls):
        globals()[name] = cls
        return cls
    return deco
