"""Durable TCPStore master: WAL-backed, wire-compatible, pure Python.

The native TCPStore server (``native/store.cc``) keeps its keys,
counters and barrier state in process memory — SIGKILL the master and
every barrier, heartbeat and staged commit in the job wedges on a store
that no longer remembers them.  This module is the durable master:

 - :class:`StoreWAL` journals every mutation (``set`` / ``add`` /
   ``delete``) as one JSON line in a per-run append-only file, fsynced
   before the op is acknowledged; :func:`replay_wal` rebuilds the
   key-value map on restart, ignoring a torn tail line (the bytes a
   mid-``write(2)`` death plausibly leaves behind).
 - :class:`DurableTCPStoreServer` speaks the exact wire protocol of
   ``store.cc`` (the native ctypes *client* connects to it unchanged),
   applies mutations through the WAL, and — when durable — maintains a
   monotonic **generation** under :data:`GENERATION_KEY`: replay bumps
   it by one, so a respawned master advertises ``gen+1`` while an
   amnesiac one (WAL lost / disabled) advertises nothing.  Clients
   (``distributed.resilient_store.ResilientStore``) fence on it: a
   reconnect that observes a LOWER generation than ever seen before is
   talking to a master that forgot their barriers, and must fail
   loudly rather than rendezvous against empty state.

Stdlib-only on purpose: the drill supervisor respawns this server via
``drill/store_master.py`` with a direct file import, so a master
restart costs a Python interpreter start — not a jax import.

Wire protocol (little-endian, mirrors ``store.cc``):
  request:  u8 op | u32 klen | key bytes | u32 vlen | value bytes
  ops: 1=SET 2=GET(nonblock) 3=WAIT(block until set) 4=ADD(v=i64 delta)
       5=DEL 6=NUMKEYS
  reply: i32 status(0 ok, -1 missing) | u32 vlen | value bytes
"""
from __future__ import annotations

import base64
import json
import logging
import os
import socket
import struct
import threading

__all__ = ["GENERATION_KEY", "StoreWAL", "replay_wal", "StoreFollower",
           "DurableTCPStoreServer", "obs_endpoint_key", "obs_world_key"]

logger = logging.getLogger(__name__)

# ASCII-decimal master generation, bumped on every WAL replay; absent on
# non-durable masters (native server, wal_path=None) so fencing stays
# inert where there is nothing durable to fence against.
GENERATION_KEY = "store/generation"


def obs_endpoint_key(run_id, process_index):
    """Canonical store key under which rank ``process_index`` of run
    ``run_id`` publishes its "host:port" metrics endpoint.  Mirrored
    (not imported — this module must stay stdlib-only and the
    observability package jax/core-free) by
    ``observability.aggregator.endpoint_key``; the test suite pins the
    two formats equal."""
    return f"obs/{run_id}/endpoint/{int(process_index)}"


def obs_world_key(run_id):
    """Canonical store key holding run ``run_id``'s expected world
    size (ASCII decimal).  Mirror of
    ``observability.aggregator.world_key``."""
    return f"obs/{run_id}/world"


_I64 = struct.Struct("<q")


def _counter_add(kv, key, delta):
    """The ADD op's 8-byte little-endian counter semantics, shared by
    the live server and WAL replay so both agree bit-for-bit."""
    cur = 0
    old = kv.get(key)
    if old is not None and len(old) == 8:
        cur = _I64.unpack(old)[0]
    cur += int(delta)
    kv[key] = _I64.pack(cur)
    return cur


def _apply_record(kv, rec):
    """Apply one WAL record to ``kv`` (replay = re-run the mutation)."""
    op = rec.get("op")
    if op == "set":
        kv[rec["k"]] = base64.b64decode(rec["v"])
    elif op == "add":
        _counter_add(kv, rec["k"], rec["d"])
    elif op == "del":
        kv.pop(rec["k"], None)
    else:
        raise ValueError(f"unknown WAL op {op!r}")


def replay_wal(path):
    """Rebuild the key-value map from a WAL file.

    A torn tail — the final line missing its newline or not parsing as
    JSON (the debris of a master SIGKILLed mid-append) — ends the
    replay at the last intact record instead of failing it; every
    acknowledged mutation was fsynced as a complete line, so only an
    unacknowledged trailing op can be torn.  Returns ``{}`` when the
    file does not exist.
    """
    kv: dict[str, bytes] = {}
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return kv
    lines = raw.split(b"\n")
    # no trailing newline -> the final segment is a torn, unacked write
    torn = lines.pop() if lines and lines[-1] != b"" else None
    for i, line in enumerate(lines):
        if not line:
            continue
        try:
            _apply_record(kv, json.loads(line))
        except (ValueError, KeyError, TypeError) as e:
            # mid-file damage: stop at the last intact prefix — the
            # records after a corrupt line may depend on lost state
            logger.warning("store WAL %s: stopping replay at corrupt "
                           "line %d: %s", path, i + 1, e)
            break
    if torn:
        logger.warning("store WAL %s: ignoring torn tail (%d bytes, "
                       "master died mid-append)", path, len(torn))
    return kv


class StoreWAL:
    """Append-only mutation journal; one fsynced JSON line per op.

    ``truncate_torn=True`` (the server's append path after a replay)
    first cuts the file back to its last complete line: a torn tail is
    unacknowledged debris, and appending a fresh record directly after
    it would glue the two into one unparseable line — turning ignorable
    tail damage into mid-file corruption that ends the NEXT replay
    early.
    """

    def __init__(self, path, fsync=True, truncate_torn=False):
        self.path = path
        self.fsync = fsync
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if truncate_torn:
            self._truncate_torn_tail(path)
        self._f = open(path, "ab")
        self._lock = threading.Lock()

    @staticmethod
    def _truncate_torn_tail(path):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        if not raw or raw.endswith(b"\n"):
            return
        keep = raw.rfind(b"\n") + 1  # 0 when no complete line exists
        logger.warning("store WAL %s: truncating %d torn tail bytes "
                       "before appending", path, len(raw) - keep)
        with open(path, "r+b") as f:
            f.truncate(keep)
            f.flush()
            os.fsync(f.fileno())

    def _append(self, rec):
        data = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
        with self._lock:
            self._f.write(data)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())

    def record_set(self, key, value):
        self._append({"op": "set", "k": key,
                      "v": base64.b64encode(value).decode("ascii")})

    def record_add(self, key, delta):
        self._append({"op": "add", "k": key, "d": int(delta)})

    def record_delete(self, key):
        self._append({"op": "del", "k": key})

    def close(self):
        with self._lock:
            try:
                self._f.close()
            except OSError as e:
                logger.warning("store WAL %s: close failed: %s",
                               self.path, e)


class StoreFollower:
    """Hot standby: incrementally tails a master's WAL into an
    in-memory key-value map, ready to be promoted the moment the
    master dies.

    The follower never serves and never writes — it only reads the WAL
    file the (possibly still-running) master appends to, applying each
    COMPLETE newline-terminated record through the same
    :func:`_apply_record` the replay path uses.  A partial line at EOF
    is the master mid-``write(2)``: the bytes are buffered and applied
    once the rest arrives, never half-applied.  A complete line that
    fails to parse is mid-file corruption: the follower stops applying
    (``self.broken`` names the damage) so promotion can never serve
    state past a hole.

    :meth:`promote` is the failover: one final catch-up poll, then a
    serving :class:`DurableTCPStoreServer` seeded from the tailed map —
    no full-file re-replay — appending to the SAME WAL with the
    generation bumped, so ``ResilientStore`` clients re-resolve onto a
    strictly higher generation and their fence holds.
    """

    def __init__(self, wal_path):
        self.wal_path = wal_path
        self.kv: dict[str, bytes] = {}
        self.records_applied = 0
        self.broken = None  # description of mid-file damage, or None
        self._pos = 0       # file offset of the first unconsumed byte
        self._buf = b""     # partial (torn-so-far) line at the tail

    def poll(self):
        """Consume every complete WAL line appended since the last
        poll; returns the number of records applied by this call."""
        if self.broken is not None:
            return 0
        try:
            with open(self.wal_path, "rb") as f:
                f.seek(self._pos)
                chunk = f.read()
        except FileNotFoundError:
            return 0
        if not chunk:
            return 0
        self._pos += len(chunk)
        data = self._buf + chunk
        lines = data.split(b"\n")
        self._buf = lines.pop()  # b"" when data ends with a newline
        applied = 0
        for line in lines:
            if not line:
                continue
            try:
                _apply_record(self.kv, json.loads(line))
            except (ValueError, KeyError, TypeError) as e:
                self.broken = (f"corrupt WAL line after "
                               f"{self.records_applied} records: {e}")
                logger.warning("store follower %s: %s — no further "
                               "records will be applied", self.wal_path,
                               self.broken)
                return applied
            applied += 1
            self.records_applied += 1
        return applied

    @property
    def generation(self):
        """Master generation as tailed so far (None before the first
        generation record arrives)."""
        raw = self.kv.get(GENERATION_KEY)
        if raw is None:
            return None
        try:
            return int(raw.decode("ascii"))
        except (ValueError, UnicodeDecodeError):
            return None

    def promote(self, port=0, host="127.0.0.1", wal_fsync=True):
        """Become the master: final catch-up, then a serving
        :class:`DurableTCPStoreServer` seeded from the tailed map.

        Any bytes still torn at promote time are an unacknowledged
        write of the dead master and are dropped (the server's append
        path truncates them from the file too).  Raises RuntimeError
        when the tail hit mid-file corruption — serving state with a
        hole would violate the clients' generation-fence contract.
        """
        self.poll()
        if self.broken is not None:
            raise RuntimeError(
                f"store follower cannot promote: {self.broken}")
        if self._buf:
            logger.warning("store follower %s: dropping %d torn tail "
                           "bytes at promote (master died mid-append)",
                           self.wal_path, len(self._buf))
        return DurableTCPStoreServer(
            port=port, host=host, wal_path=self.wal_path,
            wal_fsync=wal_fsync, seed_kv=dict(self.kv))


class DurableTCPStoreServer:
    """Wire-compatible TCPStore master with optional WAL durability.

    ``wal_path=None`` behaves like the native server (volatile, no
    generation key).  With a WAL, construction replays the journal,
    bumps the generation, and journals every subsequent mutation before
    acknowledging it — so a respawn restores keys, ADD counters and
    barrier arrival state exactly, and advertises a strictly higher
    generation than any client has seen.  ``seed_kv`` (a promoted
    :class:`StoreFollower`'s tailed map) replaces the full-file replay:
    the state was already built incrementally, so construction costs
    one generation bump, not a re-read of the journal.
    """

    def __init__(self, port=0, host="127.0.0.1", wal_path=None,
                 wal_fsync=True, seed_kv=None):
        if seed_kv is not None:
            self._kv = dict(seed_kv)
        else:
            self._kv = replay_wal(wal_path) if wal_path else {}
        self._wal = StoreWAL(wal_path, fsync=wal_fsync,
                             truncate_torn=True) if wal_path \
            else None
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._stop = False
        self.generation = None
        if self._wal is not None:
            prev = self._kv.get(GENERATION_KEY, b"0")
            try:
                gen = int(prev.decode("ascii") or 0) + 1
            except (ValueError, UnicodeDecodeError):
                logger.warning("store WAL %s: unparseable generation "
                               "%r; restarting at 1", wal_path, prev)
                gen = 1
            self.generation = gen
            value = str(gen).encode("ascii")
            self._kv[GENERATION_KEY] = value
            self._wal.record_set(GENERATION_KEY, value)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, int(port)))
        self._listen.listen(128)
        self.host = host
        self.port = self._listen.getsockname()[1]
        self._conns: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="pt-store-accept", daemon=True)
        self._accept_thread.start()

    # -- plumbing -----------------------------------------------------------

    @staticmethod
    def _read_full(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _accept_loop(self):
        while True:
            try:
                conn, _addr = self._listen.accept()
            except OSError:
                return  # listener closed by stop()
            with self._mu:
                if self._stop:
                    conn.close()
                    return
                self._conns.append(conn)
                t = threading.Thread(target=self._serve_conn,
                                     args=(conn,), daemon=True)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                head = self._read_full(conn, 5)
                if head is None:
                    return
                op, klen = struct.unpack("<BI", head)
                key = self._read_full(conn, klen) if klen else b""
                if key is None:
                    return
                vraw = self._read_full(conn, 4)
                if vraw is None:
                    return
                (vlen,) = struct.unpack("<I", vraw)
                val = self._read_full(conn, vlen) if vlen else b""
                if val is None:
                    return
                status, out = self._handle(op, key.decode("utf-8"), val)
                reply = struct.pack("<iI", status, len(out)) + out
                conn.sendall(reply)
        except OSError:
            return  # peer died / stop() shut the socket down
        finally:
            try:
                conn.close()
            except OSError:
                # fd already gone (stop() raced the handler); nothing
                # left to release
                return

    # -- op dispatch --------------------------------------------------------

    def _handle(self, op, key, val):
        """Returns (status, reply_bytes).  Mutations journal-then-apply
        under the lock so the WAL and the live map never diverge."""
        if op == 1:  # SET
            with self._cv:
                if self._wal is not None:
                    self._wal.record_set(key, val)
                self._kv[key] = val
                self._cv.notify_all()
            return 0, b""
        if op == 2:  # GET (nonblocking)
            with self._mu:
                v = self._kv.get(key)
            return (-1, b"") if v is None else (0, v)
        if op == 3:  # WAIT (block until the key exists)
            with self._cv:
                self._cv.wait_for(
                    lambda: self._stop or key in self._kv)
                v = self._kv.get(key)
            return (-1, b"") if v is None else (0, v)
        if op == 4:  # ADD (atomic i64 counter)
            delta = _I64.unpack(val)[0] if len(val) == 8 else 0
            with self._cv:
                if self._wal is not None:
                    self._wal.record_add(key, delta)
                cur = _counter_add(self._kv, key, delta)
                self._cv.notify_all()
            return 0, _I64.pack(cur)
        if op == 5:  # DEL
            with self._mu:
                if self._wal is not None:
                    self._wal.record_delete(key)
                self._kv.pop(key, None)
            return 0, b""
        if op == 6:  # NUMKEYS
            with self._mu:
                n = len(self._kv)
            return 0, _I64.pack(n)
        return -1, b""

    def num_keys(self):
        with self._mu:
            return len(self._kv)

    def stop(self):
        """Graceful shutdown (tests / clean exits — the drill's weapon
        against this server is SIGKILL, which runs none of this)."""
        with self._cv:
            if self._stop:
                return
            self._stop = True
            self._cv.notify_all()
        try:
            self._listen.close()
        except OSError as e:
            logger.debug("store server: listener close failed: %s", e)
        with self._mu:
            conns, self._conns = self._conns, []
            threads, self._threads = self._threads, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                # already closed by its handler thread — the handler
                # owns the close; nothing to unwind here
                continue
        if self._accept_thread.is_alive():
            self._accept_thread.join(timeout=5.0)
        for t in threads:
            t.join(timeout=5.0)
        if self._wal is not None:
            self._wal.close()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass
