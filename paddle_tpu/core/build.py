"""Build the native runtime core (libptcore.so) on demand.

The reference ships its native core prebuilt via CMake
(paddle/scripts/paddle_build.sh); here the core is small enough to compile
at first import with g++ and cache by source hash, which keeps the package
pip-less and hermetic. Rebuilds happen only when a source file changes.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

_SRC_DIR = os.path.join(os.path.dirname(__file__), "native")
_SOURCES = ["trace.cc", "flags.cc", "alloc.cc", "workqueue.cc", "store.cc",
            "shm.cc"]
_HEADERS = ["common.h"]
# -lrt: shm_open/shm_unlink live in librt until glibc 2.34; linking it is
# harmless on newer glibc (empty archive) and required on older ones —
# without it the .so builds fine but dlopen fails with an undefined symbol
_CXXFLAGS = ["-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
             "-fvisibility=hidden"]
_LDFLAGS = ["-lrt"]

#: last build failure detail (compiler stderr / missing toolchain), for
#: callers that got None back and want the real reason
LAST_ERROR: str | None = None


def _source_hash() -> str:
    h = hashlib.sha256()
    for name in _HEADERS + _SOURCES:
        with open(os.path.join(_SRC_DIR, name), "rb") as f:
            h.update(f.read())
    # flags are part of the identity: a flag fix (e.g. adding -lrt) must
    # invalidate a cached .so built without it
    h.update(" ".join(_CXXFLAGS + _LDFLAGS).encode())
    return h.hexdigest()[:16]


def _cache_dir() -> str:
    d = os.environ.get("PADDLE_TPU_CACHE",
                       os.path.join(os.path.expanduser("~"), ".cache",
                                    "paddle_tpu"))
    os.makedirs(d, exist_ok=True)
    return d


def build_ptcore(verbose: bool = False) -> str | None:
    """Compile (or reuse) libptcore.so; returns its path, or None if no
    toolchain is available."""
    so_path = os.path.join(_cache_dir(), f"libptcore-{_source_hash()}.so")
    if os.path.exists(so_path):
        return so_path
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    # build into a temp file then atomically rename, so concurrent importers
    # (multi-process launch) never load a half-written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_cache_dir())
    os.close(fd)
    cmd = ["g++"] + _CXXFLAGS + ["-o", tmp] + srcs + _LDFLAGS
    global LAST_ERROR
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        os.unlink(tmp)
        LAST_ERROR = f"toolchain unavailable: {e!r}"
        return None
    if res.returncode != 0:
        os.unlink(tmp)
        LAST_ERROR = f"g++ failed:\n{res.stderr}"
        if verbose:
            raise RuntimeError(f"ptcore build failed:\n{res.stderr}")
        return None
    LAST_ERROR = None
    os.replace(tmp, so_path)
    return so_path
