// Native flag registry.
//
// Counterpart of the reference's gflags-backed PHI_DEFINE_EXPORTED_* flags
// (paddle/phi/core/flags.cc): a string key/value table with env-var
// (FLAGS_<name>) seeding, shared between Python (paddle_tpu.set_flags) and
// any native component that wants to consult a flag without crossing back
// into Python.
#include "common.h"

#include <cstdlib>
#include <map>

namespace ptcore {
namespace {
std::mutex g_flag_mu;
std::map<std::string, std::string> g_flags;
}  // namespace
}  // namespace ptcore

using namespace ptcore;

PT_EXPORT void pt_flag_set(const char *name, const char *value) {
  std::lock_guard<std::mutex> lk(g_flag_mu);
  g_flags[name] = value ? value : "";
}

// Returns value length, or -1 if unset (after also checking FLAGS_<name> in
// the environment, mirroring the reference's env seeding).
PT_EXPORT int64_t pt_flag_get(const char *name, char *buf, int64_t buflen) {
  std::lock_guard<std::mutex> lk(g_flag_mu);
  auto it = g_flags.find(name);
  std::string val;
  if (it != g_flags.end()) {
    val = it->second;
  } else {
    std::string env = std::string("FLAGS_") + name;
    const char *e = getenv(env.c_str());
    if (!e) return -1;
    val = e;
    g_flags[name] = val;
  }
  int64_t n = (int64_t)val.size();
  if (buf && buflen > n) {
    memcpy(buf, val.c_str(), n + 1);
  }
  return n;
}

PT_EXPORT int64_t pt_flag_count() {
  std::lock_guard<std::mutex> lk(g_flag_mu);
  return (int64_t)g_flags.size();
}
