// Host event tracer.
//
// Equivalent of the reference's RecordEvent/HostTraceLevel machinery
// (paddle/fluid/platform/profiler/event_tracing.h, host_tracer.cc): RAII
// push/pop spans per thread, collected into a global buffer and exported as
// chrome://tracing JSON (ref: chrometracing_logger.cc). Device-side timing
// comes from the XLA/jax profiler; this covers the host framework side.
#include "common.h"

#include <atomic>
#include <cstdio>
#include <thread>
#include <vector>

namespace ptcore {
namespace {

struct Event {
  std::string name;
  uint64_t start_ns;
  uint64_t end_ns;    // 0 while open; ==start for instant events
  uint64_t tid;
  uint32_t level;
  bool instant;
};

std::atomic<int> g_trace_level{0};  // 0 = disabled
std::mutex g_mu;
std::vector<Event> g_events;
uint64_t g_trace_start_ns = 0;

struct ThreadStack {
  // Spans complete strictly LIFO per thread, so staging is a stack: each pop
  // finalizes staging.back() and moves it straight to the global buffer —
  // dump/export never miss completed events from threads still inside an
  // outer span.
  std::vector<bool> open_recorded;  // false = pushed while disabled
  std::vector<Event> staging;
};
thread_local ThreadStack t_stack;

uint64_t tid_hash() {
  return std::hash<std::thread::id>()(std::this_thread::get_id()) & 0xffffff;
}

}  // namespace
}  // namespace ptcore

using namespace ptcore;

PT_EXPORT void pt_trace_enable(int level) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (g_trace_start_ns == 0) g_trace_start_ns = now_ns();
  g_trace_level.store(level > 0 ? level : 1);
}

PT_EXPORT void pt_trace_disable() { g_trace_level.store(0); }

PT_EXPORT int pt_trace_level() { return g_trace_level.load(); }

PT_EXPORT void pt_trace_push(const char *name, int level) {
  if (g_trace_level.load() < level || g_trace_level.load() == 0) {
    // record a sentinel so pop stays balanced
    t_stack.open_recorded.push_back(false);
    return;
  }
  Event e;
  e.name = name ? name : "?";
  e.start_ns = now_ns();
  e.end_ns = 0;
  e.tid = tid_hash();
  e.level = level;
  e.instant = false;
  t_stack.staging.push_back(e);
  t_stack.open_recorded.push_back(true);
}

PT_EXPORT void pt_trace_pop() {
  auto &st = t_stack;
  if (st.open_recorded.empty()) return;
  bool recorded = st.open_recorded.back();
  st.open_recorded.pop_back();
  if (!recorded) return;  // disabled-at-push sentinel
  st.staging.back().end_ns = now_ns();
  {
    std::lock_guard<std::mutex> lk(g_mu);
    g_events.push_back(std::move(st.staging.back()));
  }
  st.staging.pop_back();
}

PT_EXPORT void pt_trace_instant(const char *name) {
  if (g_trace_level.load() == 0) return;
  std::lock_guard<std::mutex> lk(g_mu);
  Event e;
  e.name = name ? name : "?";
  e.start_ns = e.end_ns = now_ns();
  e.tid = tid_hash();
  e.level = 1;
  e.instant = true;
  g_events.push_back(e);
}

PT_EXPORT int64_t pt_trace_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  return (int64_t)g_events.size();
}

PT_EXPORT void pt_trace_clear() {
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.clear();
}

static void json_escape(FILE *f, const std::string &s) {
  for (char c : s) {
    if (c == '"' || c == '\\')
      fputc('\\', f), fputc(c, f);
    else if ((unsigned char)c < 0x20)
      fprintf(f, "\\u%04x", c);
    else
      fputc(c, f);
  }
}

// Writes chrome://tracing "traceEvents" JSON (ts/dur in microseconds).
PT_EXPORT int pt_trace_dump_json(const char *path, int pid) {
  std::lock_guard<std::mutex> lk(g_mu);
  FILE *f = fopen(path, "w");
  if (!f) return -1;
  fprintf(f, "{\"traceEvents\":[\n");
  bool first = true;
  for (auto &e : g_events) {
    if (!first) fprintf(f, ",\n");
    first = false;
    double ts = (e.start_ns - g_trace_start_ns) / 1e3;
    fprintf(f, "{\"name\":\"");
    json_escape(f, e.name);
    if (e.instant) {
      fprintf(f, "\",\"ph\":\"i\",\"s\":\"t\",\"ts\":%.3f,"
                 "\"pid\":%d,\"tid\":%llu}",
              ts, pid, (unsigned long long)e.tid);
    } else {
      double dur = (e.end_ns - e.start_ns) / 1e3;
      fprintf(f, "\",\"ph\":\"X\",\"ts\":%.3f,\"dur\":%.3f,"
                 "\"pid\":%d,\"tid\":%llu,\"cat\":\"host\"}",
              ts, dur, pid, (unsigned long long)e.tid);
    }
  }
  fprintf(f, "\n]}\n");
  fclose(f);
  return 0;
}

// Fill parallel arrays with up to `cap` completed events (for the Python
// profiler's summary tables). Returns the number written. Names are copied
// into `name_buf` back-to-back, NUL-separated (name_buf_len total capacity).
PT_EXPORT int64_t pt_trace_export(uint64_t *start_ns, uint64_t *dur_ns,
                                  uint64_t *tids, char *name_buf,
                                  int64_t name_buf_len, int64_t cap) {
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t n = 0;
  int64_t off = 0;
  for (auto &e : g_events) {
    if (n >= cap) break;
    int64_t need = (int64_t)e.name.size() + 1;
    if (off + need > name_buf_len) break;
    start_ns[n] = e.start_ns - g_trace_start_ns;
    dur_ns[n] = e.end_ns - e.start_ns;
    tids[n] = e.tid;
    memcpy(name_buf + off, e.name.c_str(), need);
    off += need;
    ++n;
  }
  return n;
}
