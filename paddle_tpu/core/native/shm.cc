// POSIX shared-memory tensor transport for the DataLoader.
//
// ref: paddle/fluid/memory/allocation/mmap_allocator.cc — the reference's
// DataLoader ships worker-produced batches to the trainer through shared
// memory when use_shared_memory=True instead of pickling tensor bytes
// through a pipe. Same design here: workers write batch buffers into a
// named segment; the parent maps it, wraps the bytes zero-copy, and
// unlinks after device upload.
#include "common.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

PT_EXPORT int64_t pt_shm_create(const char* name, int64_t size) {
  int fd = shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0) return 0;
  if (ftruncate(fd, size) != 0) {
    close(fd);
    shm_unlink(name);
    return 0;
  }
  void* p = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED) {
    shm_unlink(name);
    return 0;
  }
  return static_cast<int64_t>(reinterpret_cast<intptr_t>(p));
}

PT_EXPORT int64_t pt_shm_open_map(const char* name, int64_t size) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return 0;
  void* p = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED) return 0;
  return static_cast<int64_t>(reinterpret_cast<intptr_t>(p));
}

PT_EXPORT int pt_shm_unmap(int64_t addr, int64_t size) {
  return munmap(reinterpret_cast<void*>(static_cast<intptr_t>(addr)), size);
}

PT_EXPORT int pt_shm_unlink(const char* name) { return shm_unlink(name); }
