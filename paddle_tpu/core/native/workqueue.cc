// Threadpool work queue.
//
// Native counterpart of the reference executor's workqueue
// (paddle/fluid/framework/new_executor/workqueue/): a fixed pool of worker
// threads draining a FIFO of jobs, with a drain barrier. On TPU the XLA
// executable replaces per-op async dispatch, so this pool serves the host
// side: dataloader prefetch, checkpoint shard IO, and profiler flushing.
// Jobs are C function pointers (Python hands in ctypes callbacks, which
// re-acquire the GIL themselves).
#include "common.h"

#include <condition_variable>
#include <deque>
#include <thread>
#include <vector>

namespace ptcore {
namespace {

using JobFn = void (*)(void *);

struct WorkQueue {
  std::mutex mu;
  std::condition_variable cv_job;    // workers wait for jobs
  std::condition_variable cv_drain;  // waiters wait for quiescence
  std::deque<std::pair<JobFn, void *>> jobs;
  std::vector<std::thread> threads;
  int in_flight = 0;
  bool stop = false;

  explicit WorkQueue(int n) {
    for (int i = 0; i < n; ++i)
      threads.emplace_back([this] { worker(); });
  }

  void worker() {
    for (;;) {
      std::pair<JobFn, void *> job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_job.wait(lk, [this] { return stop || !jobs.empty(); });
        if (stop && jobs.empty()) return;
        job = jobs.front();
        jobs.pop_front();
        ++in_flight;
      }
      job.first(job.second);
      {
        std::lock_guard<std::mutex> lk(mu);
        --in_flight;
        if (jobs.empty() && in_flight == 0) cv_drain.notify_all();
      }
    }
  }

  ~WorkQueue() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_job.notify_all();
    for (auto &t : threads) t.join();
  }
};

}  // namespace
}  // namespace ptcore

using namespace ptcore;

PT_EXPORT void *pt_wq_create(int num_threads) {
  if (num_threads <= 0) num_threads = 1;
  return new WorkQueue(num_threads);
}

PT_EXPORT void pt_wq_submit(void *h, void (*fn)(void *), void *arg) {
  auto *q = (WorkQueue *)h;
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->jobs.emplace_back(fn, arg);
  }
  q->cv_job.notify_one();
}

// Block until every submitted job has finished.
PT_EXPORT void pt_wq_wait(void *h) {
  auto *q = (WorkQueue *)h;
  std::unique_lock<std::mutex> lk(q->mu);
  q->cv_drain.wait(lk, [q] { return q->jobs.empty() && q->in_flight == 0; });
}

PT_EXPORT void pt_wq_destroy(void *h) { delete (WorkQueue *)h; }

PT_EXPORT int64_t pt_wq_pending(void *h) {
  auto *q = (WorkQueue *)h;
  std::lock_guard<std::mutex> lk(q->mu);
  return (int64_t)q->jobs.size() + q->in_flight;
}
