// Auto-growth best-fit host allocator.
//
// Native counterpart of the reference's AutoGrowthBestFitAllocator
// (paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.h:30) and
// the StatAllocator stats plumbing: on TPU the *device* heap belongs to
// XLA/PJRT, so this pool serves host-side staging buffers (dataloader
// batches, checkpoint shards) where malloc/free churn and page faults would
// otherwise eat into input-pipeline throughput.
//
// Strategy (same shape as the reference):
//  - carve aligned blocks out of large chunks obtained from the system
//  - free blocks kept in a size-ordered multimap (best fit)
//  - adjacent free blocks within a chunk are coalesced on free
//  - chunks grow geometrically; idle chunks released on demand
#include "common.h"

#include <cstdlib>
#include <map>
#include <set>
#include <vector>

namespace ptcore {
namespace {

constexpr size_t kAlign = 64;
constexpr size_t kMinChunk = 1 << 20;  // 1 MiB

struct Chunk;

struct Block {
  char *ptr;
  size_t size;
  bool free_;
  Chunk *chunk;
  Block *prev = nullptr;  // address-ordered neighbors within chunk
  Block *next = nullptr;
};

struct Chunk {
  char *base;
  size_t size;
  Block *first;
};

struct Pool {
  std::mutex mu;
  std::multimap<size_t, Block *> free_blocks;  // size -> block
  std::map<char *, Block *> by_ptr;            // allocated blocks
  std::vector<Chunk *> chunks;
  size_t allocated = 0;  // bytes handed out
  size_t reserved = 0;   // bytes obtained from the system
  size_t peak = 0;
  size_t next_chunk = kMinChunk;

  void erase_free(Block *b) {
    auto range = free_blocks.equal_range(b->size);
    for (auto it = range.first; it != range.second; ++it)
      if (it->second == b) {
        free_blocks.erase(it);
        return;
      }
  }
};

Pool g_pool;

size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

}  // namespace
}  // namespace ptcore

using namespace ptcore;

PT_EXPORT void *pt_alloc(size_t n) {
  if (n == 0) n = kAlign;
  n = align_up(n);
  std::lock_guard<std::mutex> lk(g_pool.mu);
  // best fit from the free map
  auto it = g_pool.free_blocks.lower_bound(n);
  Block *b = nullptr;
  if (it != g_pool.free_blocks.end()) {
    b = it->second;
    g_pool.free_blocks.erase(it);
  } else {
    // grow: new chunk at least max(n, next_chunk)
    size_t csize = g_pool.next_chunk;
    if (csize < n) csize = align_up(n);
    g_pool.next_chunk = csize * 2;
    char *base = (char *)aligned_alloc(kAlign, csize);
    if (!base) return nullptr;
    Chunk *c = new Chunk{base, csize, nullptr};
    b = new Block{base, csize, false, c};
    c->first = b;
    g_pool.chunks.push_back(c);
    g_pool.reserved += csize;
  }
  // split if worthwhile
  if (b->size >= n + kAlign) {
    Block *rest = new Block{b->ptr + n, b->size - n, true, b->chunk};
    rest->prev = b;
    rest->next = b->next;
    if (b->next) b->next->prev = rest;
    b->next = rest;
    b->size = n;
    g_pool.free_blocks.emplace(rest->size, rest);
  }
  b->free_ = false;
  g_pool.by_ptr[b->ptr] = b;
  g_pool.allocated += b->size;
  if (g_pool.allocated > g_pool.peak) g_pool.peak = g_pool.allocated;
  return b->ptr;
}

PT_EXPORT void pt_free(void *p) {
  if (!p) return;
  std::lock_guard<std::mutex> lk(g_pool.mu);
  auto it = g_pool.by_ptr.find((char *)p);
  if (it == g_pool.by_ptr.end()) return;  // not ours
  Block *b = it->second;
  g_pool.by_ptr.erase(it);
  g_pool.allocated -= b->size;
  b->free_ = true;
  // coalesce with next
  if (b->next && b->next->free_) {
    Block *nx = b->next;
    g_pool.erase_free(nx);
    b->size += nx->size;
    b->next = nx->next;
    if (nx->next) nx->next->prev = b;
    delete nx;
  }
  // coalesce with prev
  if (b->prev && b->prev->free_) {
    Block *pv = b->prev;
    g_pool.erase_free(pv);
    pv->size += b->size;
    pv->next = b->next;
    if (b->next) b->next->prev = pv;
    delete b;
    b = pv;
  }
  g_pool.free_blocks.emplace(b->size, b);
}

// Release chunks that are one whole free block back to the system.
// Returns bytes released.
PT_EXPORT uint64_t pt_pool_release() {
  std::lock_guard<std::mutex> lk(g_pool.mu);
  uint64_t released = 0;
  std::vector<Chunk *> keep;
  for (Chunk *c : g_pool.chunks) {
    Block *b = c->first;
    if (b->free_ && b->size == c->size && !b->next) {
      g_pool.erase_free(b);
      released += c->size;
      g_pool.reserved -= c->size;
      free(c->base);
      delete b;
      delete c;
    } else {
      keep.push_back(c);
    }
  }
  g_pool.chunks.swap(keep);
  return released;
}

PT_EXPORT void pt_pool_stats(uint64_t *allocated, uint64_t *reserved,
                             uint64_t *peak, uint64_t *chunks) {
  std::lock_guard<std::mutex> lk(g_pool.mu);
  if (allocated) *allocated = g_pool.allocated;
  if (reserved) *reserved = g_pool.reserved;
  if (peak) *peak = g_pool.peak;
  if (chunks) *chunks = g_pool.chunks.size();
}
