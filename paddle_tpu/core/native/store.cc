// TCP key-value store.
//
// Native counterpart of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:120,
// tcp_store.cc): rank-0 hosts a tiny KV server, other ranks connect as
// clients; supports SET / GET / WAIT (block until key exists) / ADD
// (atomic int64 increment, used as a barrier counter). In the TPU build the
// heavy collectives are XLA's business; the store remains the bootstrap and
// elastic-heartbeat channel (fleet.elastic, launcher rendezvous).
//
// Wire protocol (all little-endian):
//   request:  u8 op | u32 klen | key bytes | u32 vlen | value bytes
//   ops: 1=SET 2=GET(nonblock) 3=WAIT(get, block until set) 4=ADD(v=i64 delta)
//        5=DEL 6=NUMKEYS
//   reply: i32 status(0 ok, -1 missing) | u32 vlen | value bytes
#include "common.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <thread>
#include <vector>

namespace ptcore {
namespace {

bool read_full(int fd, void *buf, size_t n) {
  char *p = (char *)buf;
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_full(int fd, const void *buf, size_t n) {
  const char *p = (const char *)buf;
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

struct Conn {
  std::thread th;
  // owner handoff: whichever of {conn thread, server stop} exchanges the fd
  // to -1 first closes it, so a recycled descriptor is never touched
  std::atomic<int> fd{-1};
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::mutex mu;
  std::condition_variable cv;  // signaled on every SET/ADD
  std::map<std::string, std::string> kv;
  std::deque<Conn> conns;
  std::thread accept_thread;
  std::atomic<bool> stop{false};

  void serve_conn(Conn *conn, int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    for (;;) {
      uint8_t op;
      uint32_t klen, vlen;
      if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
      std::string key(klen, 0);
      if (klen && !read_full(fd, key.data(), klen)) break;
      if (!read_full(fd, &vlen, 4)) break;
      std::string val(vlen, 0);
      if (vlen && !read_full(fd, val.data(), vlen)) break;

      int32_t status = 0;
      std::string out;
      switch (op) {
        case 1: {  // SET
          std::lock_guard<std::mutex> lk(mu);
          kv[key] = val;
          cv.notify_all();
          break;
        }
        case 2: {  // GET
          std::lock_guard<std::mutex> lk(mu);
          auto it = kv.find(key);
          if (it == kv.end())
            status = -1;
          else
            out = it->second;
          break;
        }
        case 3: {  // WAIT (blocking get)
          std::unique_lock<std::mutex> lk(mu);
          cv.wait(lk, [&] { return stop.load() || kv.count(key); });
          if (stop.load() && !kv.count(key)) {
            status = -1;
          } else {
            out = kv[key];
          }
          break;
        }
        case 4: {  // ADD
          int64_t delta = 0;
          if (val.size() == 8) memcpy(&delta, val.data(), 8);
          std::lock_guard<std::mutex> lk(mu);
          int64_t cur = 0;
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string enc(8, 0);
          memcpy(enc.data(), &cur, 8);
          kv[key] = enc;
          out = enc;
          cv.notify_all();
          break;
        }
        case 5: {  // DEL
          std::lock_guard<std::mutex> lk(mu);
          kv.erase(key);
          break;
        }
        case 6: {  // NUMKEYS
          std::lock_guard<std::mutex> lk(mu);
          int64_t n = (int64_t)kv.size();
          std::string enc(8, 0);
          memcpy(enc.data(), &n, 8);
          out = enc;
          break;
        }
        default:
          status = -1;
      }
      uint32_t olen = (uint32_t)out.size();
      if (!write_full(fd, &status, 4) || !write_full(fd, &olen, 4)) break;
      if (olen && !write_full(fd, out.data(), olen)) break;
    }
    // close under the server mutex so stop() can never shutdown a
    // recycled descriptor
    std::lock_guard<std::mutex> lk(mu);
    int owned = conn->fd.exchange(-1);
    if (owned >= 0) ::close(owned);
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) return;
        continue;
      }
      if (stop.load()) {
        ::close(fd);
        return;
      }
      std::lock_guard<std::mutex> lk(mu);
      conns.emplace_back();
      Conn *c = &conns.back();
      c->fd.store(fd);
      c->th = std::thread([this, c, fd] { serve_conn(c, fd); });
    }
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;  // one request in flight at a time

  int64_t request(uint8_t op, const std::string &key, const std::string &val,
                  std::string *out) {
    std::lock_guard<std::mutex> lk(mu);
    uint32_t klen = (uint32_t)key.size(), vlen = (uint32_t)val.size();
    if (!write_full(fd, &op, 1) || !write_full(fd, &klen, 4) ||
        (klen && !write_full(fd, key.data(), klen)) ||
        !write_full(fd, &vlen, 4) ||
        (vlen && !write_full(fd, val.data(), vlen)))
      return -2;
    int32_t status;
    uint32_t olen;
    if (!read_full(fd, &status, 4) || !read_full(fd, &olen, 4)) return -2;
    std::string buf(olen, 0);
    if (olen && !read_full(fd, buf.data(), olen)) return -2;
    if (out) *out = std::move(buf);
    return status;
  }
};

}  // namespace
}  // namespace ptcore

using namespace ptcore;

// Start a server on `port` (0 = ephemeral). Returns handle or null.
PT_EXPORT void *pt_store_server_start(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (::bind(fd, (sockaddr *)&addr, sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr *)&addr, &alen);
  auto *s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

PT_EXPORT int pt_store_server_port(void *h) { return ((Server *)h)->port; }

PT_EXPORT void pt_store_server_stop(void *h) {
  auto *s = (Server *)h;
  s->stop.store(true);
  s->cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // wake every connection thread (blocked in read_full or cv.wait) and
  // join it before freeing the server — no detached thread may outlive `s`
  {
    std::lock_guard<std::mutex> lk(s->mu);
    for (auto &c : s->conns) {
      int fd = c.fd.load();
      if (fd >= 0) ::shutdown(fd, SHUT_RDWR);  // conn thread closes it
    }
  }
  for (auto &c : s->conns)
    if (c.th.joinable()) c.th.join();
  delete s;
}

PT_EXPORT void *pt_store_client_connect(const char *host, int port,
                                        int timeout_ms) {
  uint64_t deadline = now_ns() + (uint64_t)timeout_ms * 1000000ull;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  for (;;) {
    // resolve each attempt (DNS may come up after the process does)
    addrinfo hints{};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo *res = nullptr;
    if (getaddrinfo(host, portstr, &hints, &res) == 0) {
      for (addrinfo *ai = res; ai; ai = ai->ai_next) {
        int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          freeaddrinfo(res);
          auto *c = new Client();
          c->fd = fd;
          return c;
        }
        ::close(fd);
      }
      freeaddrinfo(res);
    }
    if (now_ns() >= deadline) return nullptr;
    usleep(50 * 1000);
  }
}

PT_EXPORT int pt_store_set(void *h, const char *key, const void *val,
                           int64_t len) {
  std::string v((const char *)val, (size_t)len);
  return (int)((Client *)h)->request(1, key, v, nullptr);
}

// GET/WAIT: returns value length (copied into buf up to buflen), -1 missing
// (GET only), -2 connection error.
PT_EXPORT int64_t pt_store_get(void *h, const char *key, void *buf,
                               int64_t buflen, int wait) {
  std::string out;
  int64_t st = ((Client *)h)->request(wait ? 3 : 2, key, "", &out);
  if (st < 0) return st;
  int64_t n = (int64_t)out.size();
  if (buf && buflen >= n) memcpy(buf, out.data(), n);
  return n;
}

PT_EXPORT int64_t pt_store_add(void *h, const char *key, int64_t delta) {
  std::string v(8, 0);
  memcpy(v.data(), &delta, 8);
  std::string out;
  int64_t st = ((Client *)h)->request(4, key, v, &out);
  if (st < 0 || out.size() != 8) return INT64_MIN;
  int64_t cur;
  memcpy(&cur, out.data(), 8);
  return cur;
}

PT_EXPORT int pt_store_del(void *h, const char *key) {
  return (int)((Client *)h)->request(5, key, "", nullptr);
}

PT_EXPORT int64_t pt_store_numkeys(void *h) {
  std::string out;
  int64_t st = ((Client *)h)->request(6, "", "", &out);
  if (st < 0 || out.size() != 8) return -1;
  int64_t n;
  memcpy(&n, out.data(), 8);
  return n;
}

PT_EXPORT void pt_store_client_close(void *h) {
  auto *c = (Client *)h;
  ::close(c->fd);
  delete c;
}
