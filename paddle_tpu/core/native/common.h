// Shared helpers for the paddle_tpu native runtime core.
//
// TPU-native counterpart of the reference's native runtime plumbing
// (paddle/phi/backends/, paddle/fluid/platform/): the XLA compiler owns the
// device compute path, so the native core is the *host* runtime around it —
// tracing, flags, host memory pooling, work queues, and the TCP key-value
// store used for rendezvous (ref: paddle/phi/core/distributed/store/
// tcp_store.h:120).
#pragma once

#include <cstdint>
#include <cstring>
#include <chrono>
#include <mutex>
#include <string>

#if defined(_WIN32)
#error "paddle_tpu native core targets POSIX"
#endif

#define PT_EXPORT extern "C" __attribute__((visibility("default")))

namespace ptcore {

inline uint64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace ptcore
