"""Native runtime core bindings (ctypes over libptcore.so).

The TPU compute path is jax/XLA; this package is the native *host* runtime
around it, the part of the reference that is C++ and stays C++ here:

 - host tracer        — RecordEvent spans + chrome trace export
                        (ref: paddle/fluid/platform/profiler/event_tracing.h)
 - flag registry      — shared native/python flag table
                        (ref: paddle/phi/core/flags.cc)
 - host buffer pool   — auto-growth best-fit allocator + stats
                        (ref: paddle/fluid/memory/allocation/
                         auto_growth_best_fit_allocator.h:30)
 - work queue         — threadpool for input-pipeline/IO jobs
                        (ref: paddle/fluid/framework/new_executor/workqueue/)
 - TCPStore           — rendezvous / elastic heartbeat KV store
                        (ref: paddle/phi/core/distributed/store/tcp_store.h:120)

If no C++ toolchain is available the pure-Python fallbacks below keep every
API working (slower, same semantics) — mirroring the reference's CPU
fallback philosophy.
"""
from __future__ import annotations

import ctypes
import os
import threading

from .build import build_ptcore

__all__ = [
    "native_available", "RecordEvent", "tracer_enable", "tracer_disable",
    "tracer_dump", "tracer_clear", "tracer_events", "HostBufferPool",
    "host_memory_stats", "WorkQueue", "TCPStore",
    "DurableTCPStoreServer", "StoreWAL", "replay_wal", "GENERATION_KEY",
    "obs_endpoint_key", "obs_world_key",
]

from .store_server import (  # noqa: E402  (stdlib-only, no cycle)
    GENERATION_KEY, DurableTCPStoreServer, StoreWAL, replay_wal,
    obs_endpoint_key, obs_world_key,
)

_lib = None
_lib_err = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
        _lib_err = "disabled by PADDLE_TPU_DISABLE_NATIVE"
        return None
    path = build_ptcore()
    if path is None:
        from . import build as _build
        _lib_err = _build.LAST_ERROR or "no C++ toolchain"
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        # a stale/incompatible cached .so (e.g. built against a different
        # glibc) must degrade to the pure-python fallbacks, not crash
        # every importer
        _lib_err = f"cannot dlopen {path}: {e}"
        return None
    # --- signatures ---
    lib.pt_trace_push.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.pt_trace_dump_json.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.pt_trace_export.restype = ctypes.c_int64
    lib.pt_trace_export.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int64]
    lib.pt_trace_count.restype = ctypes.c_int64
    lib.pt_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.pt_flag_get.restype = ctypes.c_int64
    lib.pt_flag_get.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.c_int64]
    lib.pt_alloc.restype = ctypes.c_void_p
    lib.pt_alloc.argtypes = [ctypes.c_size_t]
    lib.pt_free.argtypes = [ctypes.c_void_p]
    lib.pt_pool_release.restype = ctypes.c_uint64
    lib.pt_pool_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)] * 4
    lib.pt_shm_create.restype = ctypes.c_int64
    lib.pt_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.pt_shm_open_map.restype = ctypes.c_int64
    lib.pt_shm_open_map.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.pt_shm_unmap.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.pt_shm_unlink.argtypes = [ctypes.c_char_p]
    lib.pt_wq_create.restype = ctypes.c_void_p
    lib.pt_wq_create.argtypes = [ctypes.c_int]
    lib.pt_wq_submit.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_void_p]
    lib.pt_wq_wait.argtypes = [ctypes.c_void_p]
    lib.pt_wq_destroy.argtypes = [ctypes.c_void_p]
    lib.pt_wq_pending.restype = ctypes.c_int64
    lib.pt_wq_pending.argtypes = [ctypes.c_void_p]
    lib.pt_store_server_start.restype = ctypes.c_void_p
    lib.pt_store_server_start.argtypes = [ctypes.c_int]
    lib.pt_store_server_port.restype = ctypes.c_int
    lib.pt_store_server_port.argtypes = [ctypes.c_void_p]
    lib.pt_store_server_stop.argtypes = [ctypes.c_void_p]
    lib.pt_store_client_connect.restype = ctypes.c_void_p
    lib.pt_store_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                            ctypes.c_int]
    lib.pt_store_set.restype = ctypes.c_int
    lib.pt_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int64]
    lib.pt_store_get.restype = ctypes.c_int64
    lib.pt_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int64,
                                 ctypes.c_int]
    lib.pt_store_add.restype = ctypes.c_int64
    lib.pt_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int64]
    lib.pt_store_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pt_store_numkeys.restype = ctypes.c_int64
    lib.pt_store_numkeys.argtypes = [ctypes.c_void_p]
    lib.pt_store_client_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    # replay flags set before the native core loaded, so both tables agree
    try:
        from ..framework import flags as _flags
        for name, value in list(_flags._values.items()):
            lib.pt_flag_set(name.encode(), str(value).encode())
    except Exception:
        pass
    return _lib


def native_available() -> bool:
    return _load() is not None


# --------------------------------------------------------------------------
# Tracer
# --------------------------------------------------------------------------
_py_events: list[tuple[str, float, float, int]] = []
_py_trace_on = False
_py_mu = threading.Lock()

# observability span sink: every RecordEvent is forwarded to
# paddle_tpu.observability.trace when that tracer is enabled, making it
# the single sink for host spans.  Resolved lazily (core must not
# import observability at module level) and cached as the *getter* so a
# test-reset tracer singleton is picked up.
_obs_get = None


def _obs_tracer():
    global _obs_get
    if _obs_get is None:
        try:
            from ..observability.trace import get_tracer as _g
            _obs_get = _g
        except Exception:
            _obs_get = False
    return _obs_get() if _obs_get else None


def tracer_enable(level: int = 1) -> None:
    lib = _load()
    if lib:
        lib.pt_trace_enable(level)
    else:
        global _py_trace_on
        _py_trace_on = True


def tracer_disable() -> None:
    lib = _load()
    if lib:
        lib.pt_trace_disable()
    else:
        global _py_trace_on
        _py_trace_on = False


def tracer_clear() -> None:
    lib = _load()
    if lib:
        lib.pt_trace_clear()
    with _py_mu:
        _py_events.clear()


class RecordEvent:
    """RAII host span (ref: ``platform/profiler/event_tracing.h`` RecordEvent).

    Usable as a context manager or decorator::

        with core.RecordEvent("forward"):
            ...
    """

    def __init__(self, name: str, level: int = 1):
        self.name = name
        self.level = level

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        tr = _obs_tracer()
        if tr is not None and tr.enabled:
            import time
            self._obs_t0 = time.perf_counter_ns()
        lib = _load()
        if lib:
            lib.pt_trace_push(self.name.encode(), self.level)
        elif _py_trace_on:
            import time
            self._t0 = time.perf_counter_ns()

    def end(self):
        t0 = getattr(self, "_obs_t0", None)
        if t0 is not None:
            self._obs_t0 = None
            tr = _obs_tracer()
            if tr is not None and tr.enabled:
                import time
                tr.record_span(self.name, "host", t0,
                               time.perf_counter_ns())
        lib = _load()
        if lib:
            lib.pt_trace_pop()
        elif _py_trace_on and hasattr(self, "_t0"):
            import time
            with _py_mu:
                _py_events.append((self.name, self._t0,
                                   time.perf_counter_ns(),
                                   threading.get_ident() & 0xFFFFFF))

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with RecordEvent(self.name, self.level):
                return fn(*a, **k)
        return wrapper


def tracer_dump(path: str, pid: int | None = None) -> None:
    """Export collected host events as chrome://tracing JSON."""
    lib = _load()
    if lib:
        rc = lib.pt_trace_dump_json(path.encode(),
                                    os.getpid() if pid is None else pid)
        if rc != 0:
            raise OSError(f"cannot write trace to {path}")
        return
    import json
    with _py_mu, open(path, "w") as f:
        t0 = min((e[1] for e in _py_events), default=0)
        json.dump({"traceEvents": [
            {"name": n, "ph": "X", "ts": (s - t0) / 1e3,
             "dur": (e - s) / 1e3,
             "pid": os.getpid() if pid is None else pid, "tid": t,
             "cat": "host"}
            for (n, s, e, t) in _py_events]}, f)


def tracer_events(cap: int = 65536):
    """Return completed host events as a list of
    ``(name, start_ns, dur_ns, tid)`` for summary tables."""
    lib = _load()
    if not lib:
        with _py_mu:
            return [(n, s, e - s, t) for (n, s, e, t) in _py_events]
    starts = (ctypes.c_uint64 * cap)()
    durs = (ctypes.c_uint64 * cap)()
    tids = (ctypes.c_uint64 * cap)()
    name_buf = ctypes.create_string_buffer(cap * 48)
    n = lib.pt_trace_export(starts, durs, tids, name_buf, len(name_buf), cap)
    names = bytes(name_buf.raw[:]).split(b"\0")
    out = []
    for i in range(n):
        out.append((names[i].decode(errors="replace"), int(starts[i]),
                    int(durs[i]), int(tids[i])))
    return out


# --------------------------------------------------------------------------
# Host buffer pool
# --------------------------------------------------------------------------
class HostBufferPool:
    """Pooled aligned host buffers (numpy-visible) for staging batches.

    ``take(nbytes)`` returns a ``(memoryview, token)``; ``give(token)``
    returns the buffer to the pool. Falls back to plain bytearrays without
    the native lib.
    """

    def take(self, nbytes: int):
        lib = _load()
        if not lib:
            buf = bytearray(nbytes)
            return memoryview(buf), buf
        ptr = lib.pt_alloc(nbytes)
        if not ptr:
            raise MemoryError(f"pt_alloc({nbytes}) failed")
        mv = memoryview((ctypes.c_ubyte * nbytes).from_address(ptr)).cast("B")
        return mv, ptr

    def give(self, token) -> None:
        lib = _load()
        if lib and isinstance(token, int):
            lib.pt_free(token)

    def release_free(self) -> int:
        lib = _load()
        return int(lib.pt_pool_release()) if lib else 0


def host_memory_stats() -> dict:
    """Pool stats (ref: paddle.device.cuda.memory_allocated family)."""
    lib = _load()
    if not lib:
        return {"allocated": 0, "reserved": 0, "peak_allocated": 0,
                "chunks": 0}
    vals = [ctypes.c_uint64() for _ in range(4)]
    lib.pt_pool_stats(*[ctypes.byref(v) for v in vals])
    return {"allocated": int(vals[0].value), "reserved": int(vals[1].value),
            "peak_allocated": int(vals[2].value), "chunks": int(vals[3].value)}


# --------------------------------------------------------------------------
# Work queue
# --------------------------------------------------------------------------
_JOB_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class WorkQueue:
    """Native threadpool; jobs are Python callables (run with GIL held by the
    ctypes callback bridge). Without the native lib, a
    ``concurrent.futures`` pool provides the same API."""

    def __init__(self, num_threads: int = 4):
        self._lib = _load()
        self._jobs: dict[int, object] = {}
        self._next = 1  # 0 would arrive as None through the c_void_p callback
        self._mu = threading.Lock()
        if self._lib:
            self._h = self._lib.pt_wq_create(num_threads)

            def trampoline(arg):
                with self._mu:
                    fn = self._jobs.pop(arg)
                try:
                    fn()
                except Exception:  # job errors must not kill the worker
                    import traceback
                    traceback.print_exc()
            self._tramp = _JOB_FN(trampoline)
        else:
            import concurrent.futures
            self._pool = concurrent.futures.ThreadPoolExecutor(num_threads)
            self._futures = []

    def submit(self, fn) -> None:
        if self._lib:
            with self._mu:
                token = self._next
                self._next += 1
                self._jobs[token] = fn
            self._lib.pt_wq_submit(self._h, ctypes.cast(self._tramp,
                                                        ctypes.c_void_p),
                                   token)
        else:
            self._futures.append(self._pool.submit(fn))

    def wait(self) -> None:
        if self._lib:
            self._lib.pt_wq_wait(self._h)
        else:
            import concurrent.futures
            concurrent.futures.wait(self._futures)
            self._futures = [f for f in self._futures if not f.done()]

    def pending(self) -> int:
        if self._lib:
            return int(self._lib.pt_wq_pending(self._h))
        return sum(1 for f in self._futures if not f.done())

    def shutdown(self) -> None:
        if self._lib:
            if getattr(self, "_h", None):
                self._lib.pt_wq_wait(self._h)
                self._lib.pt_wq_destroy(self._h)
                self._h = None
        else:
            self._pool.shutdown(wait=True)

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


# --------------------------------------------------------------------------
# TCPStore
# --------------------------------------------------------------------------
class TCPStore:
    """Key-value rendezvous store (ref: ``tcp_store.h:120``).

    ``TCPStore(host, port, is_master=True)`` starts the native server (and a
    loopback client); workers connect with ``is_master=False``. ``get``
    blocks until the key is set (the reference's semantics); ``add`` is the
    atomic counter used for barriers.

    ``is_master=True, wal_path=...`` starts the pure-Python
    :class:`~paddle_tpu.core.store_server.DurableTCPStoreServer` instead
    of the native one: every mutation is journaled to the WAL and a
    respawned master replays it, restoring keys / counters / barrier
    arrivals and bumping the ``store/generation`` fencing key.  The
    loopback client is the native ctypes client either way — the wire
    protocol is identical.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, timeout: float = 30.0,
                 wal_path: str | None = None):
        lib = _load()
        self._lib = lib
        self._server = None
        self._py_server = None
        self._client = None
        if lib is None:
            raise RuntimeError("TCPStore requires the native core "
                               f"(unavailable: {_lib_err}); use "
                               "jax.distributed rendezvous instead")
        if is_master and wal_path is not None:
            from . import store_server as _ss
            self._py_server = _ss.DurableTCPStoreServer(
                port=port, wal_path=wal_path)
            port = self._py_server.port
            host = "127.0.0.1"
        elif is_master:
            self._server = lib.pt_store_server_start(port)
            if not self._server:
                raise OSError(f"cannot bind TCPStore on port {port}")
            port = lib.pt_store_server_port(self._server)
            host = "127.0.0.1"
        self.host, self.port = host, port

        # worker connect retries under the caller's timeout: a worker
        # that races the master's bind gets connection-refused instantly
        # and must back off, not die on its single shot
        from ..utils.retry import retry_call

        def _connect():
            c = lib.pt_store_client_connect(
                host.encode(), port, int(timeout * 1000))
            if not c:
                raise TimeoutError(
                    f"cannot reach TCPStore at {host}:{port} "
                    f"within {timeout}s")
            return c

        try:
            self._client = retry_call(
                _connect, retry_on=(TimeoutError,), deadline=timeout,
                base=0.05, max_delay=1.0)
        except TimeoutError:
            self.close()
            raise

    def set(self, key: str, value: bytes | str) -> None:
        if isinstance(value, str):
            value = value.encode()
        rc = self._lib.pt_store_set(self._client, key.encode(), value,
                                    len(value))
        if rc != 0:
            raise ConnectionError(
                f"TCPStore set failed for key '{key}' at "
                f"{self.host}:{self.port} (master down or unreachable)")

    def get(self, key: str, wait: bool = True,
            timeout: float | None = None) -> bytes | None:
        """Fetch a key. ``wait=True`` blocks until the key is set — via
        client-side polling (jittered backoff) so a ``timeout`` can abort
        the wait with a diagnostic instead of hanging the whole job (the
        failure mode of a server-side blocking WAIT when a peer rank
        dies)."""
        from ..utils.retry import wait_until

        def _poll():
            buf = ctypes.create_string_buffer(1 << 20)
            n = self._lib.pt_store_get(self._client, key.encode(), buf,
                                       len(buf), 0)
            if n >= 0:
                if n > len(buf):  # value larger than buffer: retry sized
                    buf = ctypes.create_string_buffer(int(n))
                    n = self._lib.pt_store_get(self._client, key.encode(),
                                               buf, len(buf), 0)
                return (buf.raw[:n],)  # 1-tuple: b"" is a real value
            if n != -1:
                raise ConnectionError(
                    f"TCPStore get failed for key '{key}' at "
                    f"{self.host}:{self.port} (master down or "
                    f"unreachable)")
            return None

        got = _poll()
        if got is None and wait:
            try:
                got = wait_until(_poll, timeout, base=0.01, factor=1.5,
                                 max_delay=0.25, desc=f"key {key!r}")
            except TimeoutError:
                raise TimeoutError(
                    f"TCPStore: key '{key}' not set within {timeout}s "
                    f"(a peer rank may have died before rendezvous)")
        return got[0] if got is not None else None

    def add(self, key: str, delta: int = 1) -> int:
        v = self._lib.pt_store_add(self._client, key.encode(), delta)
        if v == -(2**63):
            raise ConnectionError(
                f"TCPStore add failed for key '{key}' at "
                f"{self.host}:{self.port} (master down or unreachable)")
        return int(v)

    @property
    def generation(self) -> int | None:
        """Master generation when served by a durable (WAL) server;
        ``None`` on workers and volatile masters."""
        if self._py_server is not None:
            return self._py_server.generation
        return None

    def delete(self, key: str) -> None:
        self._lib.pt_store_del(self._client, key.encode())

    def num_keys(self) -> int:
        return int(self._lib.pt_store_numkeys(self._client))

    def wait(self, keys, timeout: float = 300.0) -> None:
        import time as _time
        if isinstance(keys, str):
            keys = [keys]
        deadline = _time.monotonic() + timeout
        for k in keys:
            self.get(k, wait=True,
                     timeout=max(0.0, deadline - _time.monotonic()))

    def close(self) -> None:
        if self._client:
            self._lib.pt_store_client_close(self._client)
            self._client = None
        if self._server:
            self._lib.pt_store_server_stop(self._server)
            self._server = None
        if self._py_server is not None:
            self._py_server.stop()
            self._py_server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShmSegment:
    """Named POSIX shared-memory segment over the native core's shm.cc
    (ref ``paddle/fluid/memory/allocation/mmap_allocator.cc`` — the
    reference DataLoader's use_shared_memory transport). ``create`` in
    the producer, ``attach`` in the consumer; the consumer unlinks."""

    def __init__(self, name, size, _create):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native core unavailable: {_lib_err}")
        self.name = name
        self.size = int(size)
        fn = lib.pt_shm_create if _create else lib.pt_shm_open_map
        self._addr = fn(name.encode(), self.size)
        if not self._addr:
            raise OSError(
                f"shm {'create' if _create else 'attach'} failed: {name}")
        self._lib = lib

    @classmethod
    def create(cls, name, size):
        return cls(name, size, True)

    @classmethod
    def attach(cls, name, size):
        return cls(name, size, False)

    def buffer(self):
        if not self._addr:
            raise ValueError(f"shm segment {self.name} is closed")
        return (ctypes.c_char * self.size).from_address(self._addr)

    def close(self):
        if self._addr:
            self._lib.pt_shm_unmap(self._addr, self.size)
            self._addr = 0

    def unlink(self):
        self._lib.pt_shm_unlink(self.name.encode())

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def shm_available() -> bool:
    return _load() is not None


def shm_unlink(name: str) -> None:
    """Unlink a named segment without mapping it (cleanup path)."""
    lib = _load()
    if lib is not None:
        lib.pt_shm_unlink(name.encode())
