#!/usr/bin/env python
"""bench_serve.py — load generator for the AOT serving engine.

Open-loop arrival process (Poisson interarrivals at ``--rate``
requests/sec, or all-at-once when ``--rate 0``) against an in-process
:class:`paddle_tpu.serving.ServingEngine`, with the scheduler's
continuous-batching loop on a background thread — the same topology as
the HTTP front end minus the socket hop.

Emits ONE JSON record as the last stdout line (BENCH_* house style),
including:

 - ``latency_p50_ms`` / ``latency_p99_ms`` and tokens/sec,
 - batch occupancy and KV-pool utilization,
 - the zero-compile verdict: ``unexpected_compiles`` must be 0 after
   warmup for the run to pass (exit code 1 otherwise),
 - a ``tpu_unreachable`` fast-fail record when the device canary hangs
   (same contract as bench.py: the record still emits, rc=1, no
   stacked watchdogs).

CPU example (the tier-1-adjacent smoke used in the acceptance run):

    JAX_PLATFORMS=cpu python bench_serve.py --streams 64 --max-new 8
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--streams", type=int, default=64,
                    help="concurrent request streams to issue")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate in requests/sec "
                         "(0 = all at once)")
    ap.add_argument("--prompt-len", type=int, default=12,
                    help="max prompt length (sampled 3..N per stream)")
    ap.add_argument("--max-new", type=int, default=8,
                    help="tokens to generate per request")
    ap.add_argument("--precision", default=None,
                    choices=("fp32", "bf16", "int8"),
                    help="serve precision (overrides PT_SERVE_PRECISION; "
                         "int8 = PTQ weights + int8 paged KV-cache)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--max-seq", type=int, default=128)
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline budget in ms (0 = none); "
                         "each request samples uniformly from "
                         "[0.75x, 1.25x] so admission control sees a "
                         "distribution, not a step function")
    ap.add_argument("--canary-timeout", type=float, default=120.0,
                    help="seconds before declaring the device "
                         "unreachable (fast-fail)")
    ap.add_argument("--result-timeout", type=float, default=300.0,
                    help="per-stream result wait budget")
    ap.add_argument("--out", default=None,
                    help="also write the record to this JSON file")
    return ap.parse_args(argv)


def emit(record, out=None):
    if out:
        try:
            with open(out, "w") as f:
                json.dump(record, f, indent=2, sort_keys=True)
        except OSError as e:
            record.setdefault("errors", {})["out_file"] = str(e)
    print(json.dumps(record), flush=True)


def main(argv=None):
    args = parse_args(argv)
    t_start = time.time()
    precision = (args.precision
                 or os.environ.get("PT_SERVE_PRECISION") or "fp32")
    record = {
        "bench": "serve",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "ok": False,
        "streams": args.streams,
        "rate": args.rate,
        "max_new_tokens": args.max_new,
        "deadline_ms": args.deadline_ms or None,
        "precision": precision,
        "platform": os.environ.get("JAX_PLATFORMS", ""),
    }

    # device canary under a watchdog: if a tiny jit matmul can't finish,
    # the AOT build (dozens of compiles) never will — emit the fast-fail
    # record instead of hanging the whole bench budget
    canary_done = threading.Event()
    canary_err = []

    def _canary():
        try:
            import jax
            import jax.numpy as jnp
            x = jnp.ones((8, 8), jnp.float32)
            jax.jit(lambda a: a @ a)(x).block_until_ready()
            record["backend"] = jax.default_backend()
            canary_done.set()
        except Exception as e:  # fast failure still beats a hang
            canary_err.append(str(e))
            canary_done.set()

    threading.Thread(target=_canary, daemon=True).start()
    if not canary_done.wait(args.canary_timeout) or canary_err:
        record["tpu_unreachable"] = True
        record["error"] = (canary_err[0] if canary_err else
                           "canary watchdog timeout — device "
                           "unreachable; serve leg skipped (fast-fail)")
        record["bench_wall_sec"] = round(time.time() - t_start, 1)
        # the audit block rides the fast-fail record too, synthesized
        # inline: importing paddle_tpu here would run package init and
        # block on the same backend-init lock the canary is hung on
        record["audit"] = {"enabled": False, "programs": [],
                           "findings": 0, "by_rule": {},
                           "by_severity": {}}
        # resilience accounting rides the fast-fail record too, zeroed:
        # downstream dashboards key on the fields existing every run
        record.update({"shed_total": 0, "cancelled_total": 0,
                       "deadline_exceeded_total": 0, "goodput": None,
                       "kv_pool_dtype": None, "kv_pool_pages": None,
                       "kv_page_headroom_x": None,
                       "max_logit_divergence": None})
        emit(record, args.out)
        return 1

    from paddle_tpu.observability.telemetry import get_telemetry
    from paddle_tpu.serving import (ModelSpec, ServeConfig, ServingEngine,
                                    init_params)
    from paddle_tpu.serving.scheduler import (DeadlineExceeded,
                                              EngineSaturated,
                                              RequestShed)

    get_telemetry().enable()  # metrics + compile watcher
    # graph audit on for the AOT build: every bucket executable's traced
    # jaxpr is audited while the ladder compiles (load-time only)
    from paddle_tpu.tools.audit import runtime as audit_rt
    audit_rt.enable()

    spec = ModelSpec(vocab_size=args.vocab, hidden=args.hidden,
                     layers=args.layers, heads=args.heads,
                     max_seq_len=args.max_seq)
    cfg = ServeConfig.from_env().replace(precision=precision)
    if not os.environ.get("PT_SERVE_MAX_INFLIGHT"):
        cfg = cfg.replace(max_inflight=max(cfg.max_inflight,
                                           args.streams + 1))
    if not os.environ.get("PT_SERVE_KV_PAGES"):
        # enough headroom that admission control, not pool sizing,
        # shapes the run: ~half the streams resident at worst case
        worst = -(-(args.prompt_len + args.max_new) // cfg.page_size)
        cfg = cfg.replace(kv_pages=max(cfg.kv_pages,
                                       worst * (args.streams // 2) + 2))

    t_build0 = time.time()
    engine = ServingEngine(spec, init_params(spec, args.seed), cfg)
    record["aot_build_sec"] = round(time.time() - t_build0, 3)
    record["compiled_programs"] = engine.compiled_programs
    record["decode_buckets"] = list(engine.config.decode_buckets)
    record["prefill_buckets"] = list(engine.config.prefill_buckets)
    record["kv_pages"] = engine.config.kv_pages
    pool_snap = engine.pool.snapshot()
    record["kv_pool_dtype"] = pool_snap["dtype"]
    record["kv_pool_pages"] = pool_snap["usable_pages"]
    # admission headroom vs an fp32 pool under the SAME byte budget
    # (PT_SERVE_KV_PAGES is fp32-denominated): the int8 memory win
    record["kv_page_headroom_x"] = round(
        pool_snap["usable_pages"] / max(1, cfg.kv_pages - 1), 2)

    engine.scheduler.start()
    rng = np.random.RandomState(args.seed)
    prompts = [
        rng.randint(1, spec.vocab_size,
                    size=rng.randint(3, max(4, args.prompt_len + 1)))
        .tolist()
        for _ in range(args.streams)]

    streams = [None] * args.streams
    saturation_retries = 0
    shed_at_submit = 0
    t_load0 = time.monotonic()
    for i, prompt in enumerate(prompts):
        # open-loop Poisson arrivals: the schedule does not slow down
        # when the engine backs up — that pressure is the point
        if args.rate > 0:
            time.sleep(float(rng.exponential(1.0 / args.rate)))
        deadline_ms = (float(rng.uniform(0.75, 1.25)) * args.deadline_ms
                       if args.deadline_ms > 0 else None)
        while streams[i] is None:
            try:
                streams[i] = engine.scheduler.submit(
                    prompt, max_new_tokens=args.max_new,
                    deadline_ms=deadline_ms)
            except EngineSaturated:
                saturation_retries += 1
                time.sleep(0.002)
            except RequestShed:
                # a shed request is NOT retried — admission control
                # refusing infeasible work is the behaviour under test
                shed_at_submit += 1
                break

    errors = {}
    latencies = []
    tokens_generated = 0
    deadline_losses = 0
    for i, st in enumerate(streams):
        if st is None:
            continue  # shed at admission
        try:
            out = st.result(timeout=args.result_timeout)
            tokens_generated += len(out)
            latencies.append(st.latency)
        except DeadlineExceeded:
            deadline_losses += 1
        except Exception as e:
            errors[f"stream_{i}"] = str(e)
    t_load = time.monotonic() - t_load0
    engine.scheduler.stop()

    sched = engine.scheduler.snapshot()
    kv = engine.pool.snapshot()
    lat_ms = np.asarray([l * 1e3 for l in latencies if l is not None])
    record.update({
        "completed_streams": len(latencies),
        "errors": errors or None,
        "saturation_retries": saturation_retries,
        "load_wall_sec": round(t_load, 3),
        "tokens_generated": tokens_generated,
        "tokens_per_sec": round(tokens_generated / t_load, 2)
        if t_load > 0 else None,
        "requests_per_sec": round(len(latencies) / t_load, 2)
        if t_load > 0 else None,
        "latency_p50_ms": round(float(np.percentile(lat_ms, 50)), 3)
        if lat_ms.size else None,
        "latency_p99_ms": round(float(np.percentile(lat_ms, 99)), 3)
        if lat_ms.size else None,
        "latency_mean_ms": round(float(lat_ms.mean()), 3)
        if lat_ms.size else None,
        "batch_occupancy_mean": round(sched["batch_occupancy_mean"], 4),
        "peak_active_sequences": sched["peak_active"],
        "scheduler_steps": sched["steps"],
        "admission_refusals_kv": sched["refused_kv"],
        "kv_pages_peak_used": kv["high_watermark"],
        "kv_utilization_peak": round(
            kv["high_watermark"] / max(1, kv["usable_pages"]), 4),
        "unexpected_compiles": engine.unexpected_compiles,
        "zero_compile_after_warmup": engine.unexpected_compiles == 0,
        "healthz_ok": engine.healthz()["ok"],
        "audit": audit_rt.snapshot(),
        # resilience accounting: under a deadline regime shed/expired
        # requests are EXPECTED losses — goodput is the figure of merit
        "shed_total": sched["shed"],
        "cancelled_total": sched["cancelled"],
        "deadline_exceeded_total": sched["deadline_exceeded"],
        "goodput": round(len(latencies) / args.streams, 4)
        if args.streams else None,
    })
    # with no deadline regime every stream must complete; with one,
    # shed + expired requests are the shedder doing its job — the run
    # passes on zero UNEXPECTED errors and zero request-path compiles
    expected_done = (args.streams - shed_at_submit - deadline_losses
                     if args.deadline_ms > 0 else args.streams)
    record["ok"] = (not errors
                    and len(latencies) == expected_done
                    and engine.unexpected_compiles == 0)
    engine.close()
    # quality leg: max-logit-divergence vs the fp32 oracle, replayed
    # eagerly AFTER close (the compile sentinel is disarmed, so the
    # oracle's eager compiles can't book as request-path compiles)
    if precision == "int8":
        from paddle_tpu.serving.quant import (default_calibration_prompts,
                                              logit_divergence)
        record["max_logit_divergence"] = round(logit_divergence(
            spec, init_params(spec, args.seed),
            default_calibration_prompts(spec),
            page_size=cfg.page_size), 6)
    else:
        record["max_logit_divergence"] = 0.0
    record["bench_wall_sec"] = round(time.time() - t_start, 1)
    emit(record, args.out)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
